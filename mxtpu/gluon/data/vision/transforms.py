"""Vision transforms (reference
``python/mxnet/gluon/data/vision/transforms.py`` [path cite]).

Transforms are HybridBlocks operating on HWC uint8 images (dataset layout)
and producing CHW float tensors, exactly like the reference.
"""
from __future__ import annotations

import numpy as _np

from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomCrop",
           "RandomFlipLeftRight", "RandomFlipTopBottom", "RandomBrightness",
           "RandomContrast"]


class Compose(Sequential):
    """Sequentially composed transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def hybrid_forward(self, F, x):
        out = x.astype("float32") / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    """Channel-wise (x - mean) / std. mean/std are Constant parameters
    (initialized here, so no net.initialize() is needed): they reach
    hybrid_forward through the F-agnostic parameter path, which keeps
    the block trace-safe (mxlint MXL001) and ONNX-exportable — the old
    body called ``nd.array`` on the hot path and broke every
    hybridize()/export trace."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        mean = _np.asarray(mean, "float32").reshape(-1, 1, 1)
        std = _np.asarray(std, "float32").reshape(-1, 1, 1)
        with self.name_scope():
            self.mean = self.params.get_constant("mean", mean)
            self.std = self.params.get_constant("std", std)
        self.mean.initialize()
        self.std.initialize()

    def hybrid_forward(self, F, x, mean, std):
        return (x - mean) / std


def _resize_nd(x: NDArray, size) -> NDArray:
    import jax.image
    if isinstance(size, int):
        size = (size, size)
    h, w = size[1], size[0]  # reference Resize takes (w, h)
    if x.ndim == 3:
        new_shape = (h, w, x.shape[2])
    else:
        new_shape = (x.shape[0], h, w, x.shape[3])
    from ....ndarray.ndarray import apply_op
    return apply_op(
        lambda a: jax.image.resize(a.astype("float32"), new_shape,
                                   method="linear").astype(a.dtype),
        [x], "imresize")


class Resize(HybridBlock):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def hybrid_forward(self, F, x):
        return _resize_nd(x, self._size)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3:-1] if x.ndim == 3 else x.shape[1:3]
        if H < h or W < w:
            x = _resize_nd(x, (max(w, W), max(h, H)))
            H, W = (x.shape[0], x.shape[1]) if x.ndim == 3 else x.shape[1:3]
        y0 = (H - h) // 2
        x0 = (W - w) // 2
        if x.ndim == 3:
            return x[y0:y0 + h, x0:x0 + w, :]
        return x[:, y0:y0 + h, x0:x0 + w, :]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        w, h = self._size
        if self._pad:
            p = self._pad
            pads = [(p, p), (p, p), (0, 0)] if x.ndim == 3 else \
                [(0, 0), (p, p), (p, p), (0, 0)]
            x = nd.array(_np.pad(x.asnumpy(), pads))
        H, W = (x.shape[0], x.shape[1]) if x.ndim == 3 else x.shape[1:3]
        y0 = int(_np.random.randint(0, H - h + 1))
        x0 = int(_np.random.randint(0, W - w + 1))
        if x.ndim == 3:
            return x[y0:y0 + h, x0:x0 + w, :]
        return x[:, y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = (x.shape[0], x.shape[1]) if x.ndim == 3 else x.shape[1:3]
        area = H * W
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            w = int(round(_np.sqrt(target_area * aspect)))
            h = int(round(_np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                y0 = int(_np.random.randint(0, H - h + 1))
                x0 = int(_np.random.randint(0, W - w + 1))
                crop = x[y0:y0 + h, x0:x0 + w, :] if x.ndim == 3 else \
                    x[:, y0:y0 + h, x0:x0 + w, :]
                return _resize_nd(crop, self._size)
        return _resize_nd(x, self._size)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return nd.flip(x, axis=1 if x.ndim == 3 else 2)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return nd.flip(x, axis=0 if x.ndim == 3 else 1)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._brightness = brightness

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._brightness, self._brightness)
        return (x.astype("float32") * alpha).clip(0, 255).astype(x.dtype)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._contrast = contrast

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._contrast, self._contrast)
        xf = x.astype("float32")
        gray_mean = float(xf.mean().asscalar())
        return ((xf - gray_mean) * alpha + gray_mean).clip(0, 255) \
            .astype(x.dtype)
