"""Vision datasets + transforms (reference gluon.data.vision)."""
from .datasets import (CIFAR10, CIFAR100, FashionMNIST, ImageFolderDataset,
                       MNIST)
from . import transforms
