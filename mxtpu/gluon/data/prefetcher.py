"""Double-buffered device prefetch: overlap the host→device upload of
batch *k+1* with the jitted step running on batch *k*.

Why a separate stage: PJRT dispatch is asynchronous, but a training
loop that calls ``device_put`` (or ``nd.array``) *inline* only issues
the upload when the host thread reaches it — i.e. after the previous
step's dispatch, serializing decode+upload behind the step on the host
timeline. :class:`DevicePrefetcher` moves the pull-from-source and the
``device_put`` onto a background thread with a one-deep (configurable)
buffer, so by the time the consumer asks for batch k+1 its transfer
was issued a whole step earlier and has been overlapping compute.

The measured effect belongs to the fenced-methodology section of
docs/perf.md ("Real-data input pipeline"): on the dev box's ~26 MB/s
axon tunnel the upload dominates end-to-end real-data training, which
is exactly when hiding it behind the step pays most; on a PCIe host
the same overlap hides the (smaller) DMA cost. Transfers are lossless
— the prefetched stream is bit-identical to the source stream
(tier-1-gated in tests/test_gluon_data.py).

Works over both batch protocols:

- ``mx.io.DataIter`` sources (e.g. ``NativeImageRecordIter``) yielding
  :class:`~mxtpu.io.DataBatch` — data/label NDArrays are re-emitted
  device-resident, numpy leaves are uploaded;
- plain iterables of numpy/jax pytrees (dict/list/tuple), as used by
  ``bench.py`` and functional train steps.
"""
from __future__ import annotations

import queue as _queue
import threading
import time as _time
from typing import Any, Iterable, Optional

import numpy as _np

from ... import telemetry

__all__ = ["DevicePrefetcher"]

_SENTINEL = object()


class DevicePrefetcher:
    """Background-thread device prefetch with a bounded buffer.

    Parameters
    ----------
    source : iterable or DataIter
        Yields batches. ``reset()``/``close()`` are forwarded when the
        source has them.
    depth : int
        Batches buffered beyond the one the consumer holds (1 = classic
        double buffering: one on device computing, one in flight).
    device : optional jax device
        Target device (default: ``jax.devices()[0]``).
    timeout : float
        Seconds the consumer waits for the producer before raising —
        a stuck decode surfaces as an error, never a silent hang.
    """

    def __init__(self, source, depth: int = 1,
                 device: Optional[Any] = None, timeout: float = 120.0):
        self._source = source
        self._depth = max(1, int(depth))
        self._device = device
        self._timeout = timeout
        # queue + stop event are created PER producer generation and
        # passed into the thread: a producer that outlives a timed-out
        # join (stuck decode) keeps its own (already-stopped) pair and
        # can never touch a successor generation's state
        self._q: Optional[_queue.Queue] = None
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # the data-wait leg of the step-time split: time the CONSUMER
        # spends blocked on the producer (0 when prefetch is winning)
        self._m_wait = telemetry.histogram(
            "train_data_wait_ms",
            "Time the training loop blocked waiting for the next "
            "prefetched batch")

    # -- device placement -------------------------------------------------
    def _to_device(self, obj):
        import jax
        from ...io import DataBatch
        from ...ndarray import NDArray

        dev = self._device
        if isinstance(obj, DataBatch):
            out = DataBatch(
                data=[self._to_device(d) for d in (obj.data or [])],
                label=[self._to_device(l) for l in (obj.label or [])],
                pad=obj.pad, index=obj.index, bucket_key=obj.bucket_key,
                provide_data=obj.provide_data,
                provide_label=obj.provide_label)
            return out
        if isinstance(obj, NDArray):
            # already device-resident (nd.array device_puts at
            # construction); re-wrapping would add a device copy
            return obj
        if isinstance(obj, (_np.ndarray, _np.generic)) or \
                isinstance(obj, jax.Array):
            return jax.device_put(obj, dev)
        if isinstance(obj, dict):
            return {k: self._to_device(v) for k, v in obj.items()}
        if isinstance(obj, tuple):
            return tuple(self._to_device(v) for v in obj)
        if isinstance(obj, list):
            return [self._to_device(v) for v in obj]
        return obj

    # -- producer ---------------------------------------------------------
    @staticmethod
    def _bounded_put(q, stop, item) -> bool:
        # give up when the consumer is gone so close() can't deadlock
        # against a full queue
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _producer(self, q, stop):
        try:
            for batch in self._source:
                if stop.is_set():
                    return
                if not self._bounded_put(q, stop, self._to_device(batch)):
                    return
        except StopIteration:
            pass
        except Exception as e:          # surfaced on the consumer side
            self._bounded_put(q, stop, e)
        self._bounded_put(q, stop, _SENTINEL)

    def _ensure_started(self):
        if self._closed:
            raise RuntimeError("DevicePrefetcher is closed")
        if self._thread is None:
            self._stop = threading.Event()
            self._q = _queue.Queue(maxsize=self._depth)
            self._thread = threading.Thread(
                target=self._producer, args=(self._q, self._stop),
                daemon=True, name="mxtpu-device-prefetch")
            self._thread.start()

    def _stop_producer(self):
        if self._stop is not None:
            self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            # drain so a blocked put() notices the stop event promptly
            try:
                while True:
                    self._q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=30)
        self._q = None

    # -- consumer protocol ------------------------------------------------
    def __iter__(self):
        self._ensure_started()
        return self

    def __next__(self):
        self._ensure_started()
        t0 = _time.perf_counter()
        try:
            item = self._q.get(timeout=self._timeout)
            if item is not _SENTINEL:      # epoch-end is not data wait
                self._m_wait.observe(1e3 * (_time.perf_counter() - t0))
        except _queue.Empty:
            raise RuntimeError(
                f"DevicePrefetcher: no batch from source within "
                f"{self._timeout}s (stuck decode/upload?)") from None
        if item is _SENTINEL:
            self._thread = None         # epoch done; reset() restarts
            raise StopIteration
        if isinstance(item, Exception):
            self._stop_producer()
            raise item
        return item

    next = __next__                     # DataIter spelling

    def reset(self):
        """End the current epoch (if mid-flight), reset the source, and
        restart prefetch lazily on the next pull. The source must be
        resettable: silently resuming a plain iterator mid-stream would
        drop the in-flight buffered batches."""
        mid_flight = self._thread is not None
        self._stop_producer()
        if hasattr(self._source, "reset"):
            self._source.reset()
        elif mid_flight:
            raise RuntimeError(
                "DevicePrefetcher.reset(): source has no reset() and an "
                "epoch is mid-flight — buffered batches would be lost. "
                "Wrap a resettable iterator (DataIter/DataLoader) to use "
                "reset().")

    def close(self):
        """Stop the producer, drain the buffer, close the source."""
        if self._closed:
            return
        self._closed = True
        self._stop_producer()
        if hasattr(self._source, "close"):
            self._source.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getattr__(self, name):
        # delegate metadata (provide_data/provide_label/batch_size/...)
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_source"], name)
