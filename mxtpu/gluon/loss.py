"""Loss blocks (reference ``python/mxnet/gluon/loss.py`` [path cite]).

All losses are HybridBlocks: ``loss(pred, label[, sample_weight])`` returns
per-sample loss averaged over the batch axis per the reference's
``_apply_weighting`` + ``mean over batch_axis`` convention.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss", "CTCLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, pred, label):
    # F.reshape_like, not label.reshape(pred.shape): Symbols have no
    # .shape, so the attribute spelling breaks every hybridize()/export
    # trace (mxlint MXL001's cousin — shape-dependent eager code)
    return F.reshape_like(label, pred)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{self.__class__.__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def _mean_all_but_batch(self, F, loss):
        # exclude-mean (the reference's spelling): trace-safe — no
        # .ndim read, the axis set resolves inside the op
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = ((pred - _reshape_like(F, pred, label)) ** 2)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_all_but_batch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = (pred - _reshape_like(F, pred, label)).abs()
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, pred, label)
        if not self._from_sigmoid:
            # log(1+exp(-|x|)) + max(x,0) - x*z  — numerically stable
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-pred.abs(), act_type="softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = F.relu(pred) - pred * label + log_weight * \
                    (F.Activation(-pred.abs(), act_type="softrelu") +
                     F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -((pred + eps).log() * label +
                         (1. - pred + eps).log() * (1. - label))
            else:
                loss = -((pred + eps).log() * label * pos_weight +
                         (1. - pred + eps).log() * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """CE over softmax logits (reference ``gluon.loss.SoftmaxCrossEntropyLoss``):
    sparse labels by default, dense when sparse_label=False."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, pred, label)
            loss = -(pred * label).sum(axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * ((label + 1e-12).log() - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = (pred - _reshape_like(F, pred, label)).abs()
        # comparisons already return 0/1 in the operand dtype (both nd
        # and sym), so no .astype(loss.dtype) — Symbols have no .dtype
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * (loss ** 2))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.relu(self._margin - pred * _reshape_like(F, pred, label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.relu(self._margin - pred * _reshape_like(F, pred, label)) ** 2
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise ValueError(f"bad label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-pred.abs(), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, pred, positive)
        negative = _reshape_like(F, pred, negative)
        loss = F.sum((pred - positive) ** 2 - (pred - negative) ** 2,
                     axis=self._batch_axis, exclude=True) + self._margin
        loss = F.relu(loss)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        # MXNet reshape code 0 = keep that dim — no .shape read, so the
        # flatten-to-(batch, -1) stays trace-safe
        input1 = input1.reshape((0, -1))
        input2 = input2.reshape((0, -1))
        cos = (input1 * input2).sum(axis=1) / \
            (input1.norm(axis=1) * input2.norm(axis=1) + 1e-12)
        label = label.reshape((-1,))
        pos = 1 - cos
        neg = F.relu(cos - self._margin)
        loss = F.where(label == 1, pos, neg)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference
    ``gluon.loss.CTCLoss`` over warp-ctc). Layout TNC like the reference
    default; computed via the standard log-alpha recursion with lax.scan
    inside the op (see mxtpu/ndarray/ops.py ctc_loss)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise ValueError(f"bad layout {layout}")
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._batch_axis == 1:
            label = label.swapaxes(0, 1)
        loss = F.ctc_loss(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(F, loss, self._weight, sample_weight)
