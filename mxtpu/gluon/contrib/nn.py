"""gluon.contrib.nn (reference
``python/mxnet/gluon/contrib/nn/basic_layers.py``): structural blocks +
SyncBatchNorm (an alias here — data-parallel mesh training computes
batch stats over the global batch inside the jitted step already)."""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock
from ..nn import BatchNorm
from ..nn.basic_layers import Concatenate, HybridConcatenate

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm",
           "PixelShuffle2D"]

Concurrent = Concatenate
HybridConcurrent = HybridConcatenate


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference SyncBatchNorm over
    NCCL). Under mesh data parallelism the batch axis is one logical
    array, so plain BatchNorm already reduces over the global batch —
    this subclass exists for API parity (num_devices accepted/ignored)."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class PixelShuffle2D(HybridBlock):
    """Rearrange (B, C*f1*f2, H, W) → (B, C, H*f1, W*f2) (reference
    contrib PixelShuffle2D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = (factor, factor) if isinstance(factor, int) \
            else tuple(factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factor
        b, c, h, w = x.shape
        c_out = c // (f1 * f2)
        x = x.reshape(b, c_out, f1, f2, h, w)
        x = x.transpose((0, 1, 4, 2, 5, 3))
        return x.reshape(b, c_out, h * f1, w * f2)
