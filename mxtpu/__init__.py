"""mxtpu — a TPU-native deep-learning framework with MXNet's capabilities.

A ground-up rebuild of the Apache MXNet 1.x surface (reference:
yuantangliang/incubator-mxnet) on the JAX/XLA/Pallas stack:

- ``mx.nd`` imperative arrays  → jax.Array + async PJRT dispatch
- ``mx.autograd``              → tape over jax.vjp
- ``mx.gluon`` + hybridize()   → jax.jit whole-graph compilation
- ``mx.kv`` KVStore            → XLA collectives over the ICI mesh
- ``mx.sym`` Symbol            → lazy tracer lowering to the same ops

Typical use, unchanged from the reference except the context::

    import mxtpu as mx
    net.initialize(ctx=mx.tpu())
"""
from . import base

# Dtype policy (TPU-native): 64-bit dtypes are demoted to 32-bit by default
# — float64 has no TPU hardware path and int64 indexing costs bandwidth.
# Set MXNET_ENABLE_X64=1 before import for full 64-bit support (CPU workflows,
# the reference's large-tensor mode; tests/conftest.py enables it).
if base.env_bool("MXNET_ENABLE_X64", False,
                 "Enable 64-bit dtypes (jax_enable_x64)."):
    import jax as _jax
    _jax.config.update("jax_enable_x64", True)

# Numeric sanitizer (SURVEY §5.2; VERDICT r2 #7): the NaiveEngine
# switch serializes dispatch but cannot see INSIDE a jitted program —
# this can. Every jitted computation is checked for NaNs on return and,
# on a hit, re-run op-by-op to name the producing primitive
# (FloatingPointError). Debug tool: disables jit caching benefits.
if base.env_bool("MXTPU_DEBUG_NANS", False,
                 "Abort on NaN inside jitted programs, with op "
                 "attribution (jax_debug_nans)."):
    import jax as _jax
    _jax.config.update("jax_debug_nans", True)

# Lockset sanitizer (docs/lint.md §MXL203): patch the threading lock
# factories BEFORE any mxtpu class constructs one, so every serve/
# fleet/kvstore lock records real acquisition orders for the mxlint
# lock-graph cross-check. Loaded by file path: the normal package
# route (mxtpu.contrib.analysis) imports back through mxtpu.contrib
# and would be circular this early; registering the canonical module
# name makes later `from mxtpu.contrib.analysis import lockcheck`
# resolve to this same instance.
if base.env_bool("MXTPU_ANALYSIS_LOCKCHECK", False,
                 "Record runtime lock-acquisition orders and fail on "
                 "contradictions with the static lock graph "
                 "(diagnostic; see docs/lint.md)."):
    import importlib.util as _ilu
    import os as _os
    import sys as _sys
    _lc_path = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                             "contrib", "analysis", "lockcheck.py")
    _lc_spec = _ilu.spec_from_file_location(
        "mxtpu.contrib.analysis.lockcheck", _lc_path)
    _lockcheck = _ilu.module_from_spec(_lc_spec)
    _sys.modules[_lc_spec.name] = _lockcheck
    _lc_spec.loader.exec_module(_lockcheck)
    _lockcheck.install()

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from .ndarray import random
from . import autograd

__version__ = "0.1.0"


def __getattr__(name):
    # heavier subsystems load lazily to keep `import mxtpu` fast
    import importlib
    lazy = {"gluon", "optimizer", "metric", "initializer", "lr_scheduler",
            "callback", "kvstore", "io", "image", "symbol", "profiler",
            "test_utils", "util", "runtime", "recordio", "np", "npx",
            "sym", "model", "engine", "parallel", "models", "ops",
            "utils", "amp", "contrib", "rnn", "serde", "module", "mod",
            "monitor", "operator", "checkpoint", "native", "rtc",
            "visualization", "viz", "serve", "telemetry"}
    if name in lazy:
        mod = {"sym": "mxtpu.symbol", "np": "mxtpu.numpy",
               "npx": "mxtpu.numpy_extension",
               "rnn": "mxtpu.gluon.rnn",
               "mod": "mxtpu.module",
               "viz": "mxtpu.visualization"}.get(name, f"mxtpu.{name}")
        try:
            m = importlib.import_module(mod)
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module 'mxtpu' has no attribute {name!r}") from e
        globals()[name] = m
        return m
    if name == "kv":
        m = importlib.import_module("mxtpu.kvstore")
        globals()["kv"] = m
        return m
    raise AttributeError(f"module 'mxtpu' has no attribute {name!r}")
