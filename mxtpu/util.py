"""mx.util (reference ``python/mxnet/util.py`` [path cite — unverified]):
np-mode switches/decorators and small helpers."""
from __future__ import annotations

import functools
import os

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "use_np", "use_np_array", "use_np_shape", "np_array", "np_shape",
           "makedirs", "get_gpu_count", "get_gpu_memory"]


def set_np(shape=True, array=True, dtype=False):
    from . import numpy_extension as npx
    npx.set_np(shape=shape, array=array, dtype=dtype)


def reset_np():
    from . import numpy_extension as npx
    npx.reset_np()


def is_np_array() -> bool:
    from . import numpy_extension as npx
    return npx.is_np_array()


def is_np_shape() -> bool:
    return True


def use_np_array(func):
    """Decorator running ``func`` in np-array mode (reference
    ``mx.util.use_np_array``)."""
    from . import numpy_extension as npx

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with npx.np_array(True):
            return func(*args, **kwargs)
    return wrapper


def use_np_shape(func):
    # np-shape is always on in the rebuild (jax has numpy shape
    # semantics natively); identity decorator for API parity
    return func


def use_np(func):
    return use_np_array(use_np_shape(func))


def np_shape(active=True):
    import contextlib
    return contextlib.nullcontext()


def np_array(active=True):
    from . import numpy_extension as npx
    return npx.np_array(active)


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count() -> int:
    from .context import num_gpus
    return num_gpus()


def get_gpu_memory(gpu_dev_id: int = 0):
    raise RuntimeError("GPU memory query is not applicable on TPU; use "
                       "jax.local_devices()[i].memory_stats()")
