"""Foundation utilities: dtype handling, env-var config registry, errors.

TPU-native rebuild of the roles played in the reference by
``python/mxnet/base.py`` (ctypes glue — not needed here: the "C ABI" of
this framework is jaxlib/PJRT, already C++) and the env-var config tier
documented in the reference's ``docs/faq/env_var.md`` [path cite].
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as _np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "integer_types",
    "dtype_np",
    "dtype_name",
    "env_int",
    "env_bool",
    "env_str",
    "env_float",
    "registered_env_vars",
    "atomic_write",
    "ManifestError",
    "manifest_commit",
    "manifest_read",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: ``dmlc::Error`` surfaced via
    ``MXGetLastError``, ``src/c_api/c_api_error.cc`` [path cite])."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype table. bfloat16 is first-class on TPU (the reference's
# float16 story lives in 3rdparty/mshadow/mshadow/half.h + bfloat.h).
_DTYPE_ALIASES: Dict[str, str] = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "uint8": "uint8",
    "int8": "int8",
    "int32": "int32",
    "int64": "int64",
    "bool": "bool",
}


def dtype_np(dtype: Any) -> _np.dtype:
    """Normalize a dtype-ish value (str, np.dtype, jnp dtype, None) to np.dtype."""
    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes

            return _np.dtype(ml_dtypes.bfloat16)
        return _np.dtype(_DTYPE_ALIASES.get(dtype, dtype))
    return _np.dtype(dtype)


def dtype_name(dtype: Any) -> str:
    """Printable dtype name ('float32', 'bfloat16', ...)."""
    return _np.dtype(dtype).name


# ---------------------------------------------------------------------------
# Env-var config registry — the rebuild's analogue of the ~80 MXNET_* env
# vars read via dmlc::GetEnv and documented in docs/faq/env_var.md.
# Every knob is registered so `mxtpu.base.registered_env_vars()` is the
# single documented registry (SURVEY.md §5.6 rebuild mapping).
# ---------------------------------------------------------------------------
_ENV_REGISTRY: Dict[str, Dict[str, Any]] = {}


def _register(name: str, default: Any, doc: str) -> None:
    _ENV_REGISTRY.setdefault(name, {"default": default, "doc": doc})


def env_int(name: str, default: int, doc: str = "") -> int:
    _register(name, default, doc)
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_bool(name: str, default: bool, doc: str = "") -> bool:
    _register(name, default, doc)
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() not in ("0", "false", "off", "")


def env_str(name: str, default: str, doc: str = "") -> str:
    _register(name, default, doc)
    return os.environ.get(name, default)


def env_float(name: str, default: float, doc: str = "") -> float:
    _register(name, default, doc)
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def atomic_write(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically: tempfile in the same
    directory + fsync + ``os.replace``, so a mid-write kill (OOM,
    preemption, SIGKILL) leaves either the complete old file or the
    complete new one on disk — never a torn mix. The ONE durable-write
    helper: Trainer.save_states and the kvstore server's crash-recovery
    snapshot both go through it."""
    import tempfile
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class ManifestError(MXNetError):
    """A manifest-committed blob failed validation on read: the
    manifest itself is torn/foreign, the payload file is missing, or
    the payload's size/checksum disagrees with what the manifest
    promised. Consumers treat this as "that commit never happened" and
    fall back (previous checkpoint step, empty kvstore snapshot) —
    never as data."""


def manifest_commit(path: str, data: bytes) -> None:
    """THE durable-commit discipline for crash-recovery state (kvstore
    server snapshots and checkpoint data-position journals both ride
    it): write ``data`` to ``path + '.payload'`` (atomic), then commit
    by atomically writing a manifest at ``path`` recording the
    payload's size + sha256. ``atomic_write`` alone guarantees each
    FILE is untorn; the manifest adds end-to-end validation — a reader
    can prove the payload it found is the payload the writer meant,
    not a stale or half-committed one, and :func:`manifest_read`
    refuses anything else with :class:`ManifestError`."""
    import hashlib
    import json
    payload = os.fspath(path) + ".payload"
    atomic_write(payload, data)
    manifest = {"format": "mxtpu-manifest", "version": 1,
                "payload": os.path.basename(payload),
                "size": len(data),
                "sha256": hashlib.sha256(data).hexdigest()}
    atomic_write(path, json.dumps(manifest).encode())


def manifest_read(path: str) -> bytes:
    """Read back a :func:`manifest_commit` blob, validating size and
    checksum. Raises :class:`ManifestError` for ANY inconsistency
    (torn/foreign manifest, missing payload, checksum mismatch) and
    ``FileNotFoundError`` only when no manifest exists at all."""
    import hashlib
    import json
    path = os.fspath(path)
    with open(path, "rb") as f:
        raw = f.read()
    try:
        manifest = json.loads(raw)
        if manifest.get("format") != "mxtpu-manifest":
            raise ValueError("not an mxtpu manifest")
        payload_name = manifest["payload"]
        size = int(manifest["size"])
        sha = manifest["sha256"]
    except Exception as e:
        raise ManifestError(
            f"manifest {path!r} is torn or foreign ({e!r})") from e
    payload = os.path.join(os.path.dirname(os.path.abspath(path)),
                           payload_name)
    try:
        with open(payload, "rb") as f:
            data = f.read()
    except OSError as e:
        raise ManifestError(
            f"manifest {path!r} names payload {payload_name!r} which "
            f"cannot be read ({e})") from e
    if len(data) != size or hashlib.sha256(data).hexdigest() != sha:
        raise ManifestError(
            f"payload {payload_name!r} does not match manifest "
            f"{path!r} (size {len(data)} vs {size}) — torn or stale "
            "commit")
    return data


def registered_env_vars() -> Dict[str, Dict[str, Any]]:
    """All env vars the framework reads, with defaults and docs."""
    return dict(_ENV_REGISTRY)


# Commonly-consulted knobs registered eagerly so they always appear in the
# registry even before first use.
env_str("MXNET_ENGINE_TYPE", "ThreadedEngine",
        "Execution mode: 'NaiveEngine' forces block_until_ready after every "
        "op (sync debugging, reference src/engine/naive_engine.cc analogue); "
        "default relies on XLA async dispatch.")
env_bool("MXNET_SAFE_ACCUMULATION", True,
         "Accumulate reductions of low-precision dtypes in float32.")
env_int("MXNET_TEST_SEED", -1, "Fixed seed for the test suite (-1 = random).")
env_str("MXNET_TEST_DEVICE", "", "Device for default_context() in tests.")
