"""Device contexts: ``mx.tpu()``, ``mx.cpu()``, ``mx.gpu()``.

Rebuild of the reference's Context (``include/mxnet/base.h`` Context struct,
``python/mxnet/context.py`` [path cite]). A Context names a logical device;
it resolves lazily to a ``jax.Device``. ``mx.gpu()`` is kept as a
compatibility alias that resolves to the platform accelerator so reference
scripts run with ``ctx=mx.gpu()`` unchanged (the north-star swap is
``ctx=mx.tpu()``).
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]

_ACCEL_TYPES = ("tpu", "gpu", "axon")


class Context:
    """A logical device. devtype is 'cpu', 'tpu' or 'gpu'."""

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in ("cpu", "tpu", "gpu", "cpu_pinned", "cpu_shared"):
            raise ValueError(f"unknown device type {device_type!r}")
        # pinned/shared memory distinctions are meaningless under PJRT —
        # alias them to cpu (reference: src/storage/ pinned/shared managers).
        if device_type in ("cpu_pinned", "cpu_shared"):
            device_type = "cpu"
        self.device_type = device_type
        self.device_id = device_id

    # -- resolution ---------------------------------------------------------
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device."""
        devs = _devices_of_type(self.device_type)
        if not devs:
            raise RuntimeError(
                f"no {self.device_type} devices available "
                f"(jax backend: {jax.default_backend()})")
        return devs[self.device_id % len(devs)]

    # -- protocol -----------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()

    @classmethod
    def default(cls) -> "Context":
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _default_device()


def _devices_of_type(device_type: str) -> List[jax.Device]:
    # LOCAL devices only: under multi-process (jax.distributed) a
    # context must never resolve to another process's device — the
    # reference's ctx list was per-worker too
    all_devs = jax.local_devices()
    if device_type == "cpu":
        cpus = [d for d in all_devs if d.platform == "cpu"]
        if cpus:
            return cpus
        try:
            return jax.local_devices(backend="cpu")
        except RuntimeError:
            return []
    # 'tpu' or 'gpu': any non-cpu accelerator (axon PJRT reports its own
    # platform name for TPU).
    accel = [d for d in all_devs if d.platform != "cpu"]
    return accel


def _default_device() -> Context:
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return Context("tpu", 0) if accel else Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias: resolves to the platform accelerator."""
    return Context("gpu", device_id)


def current_context() -> Context:
    return Context.default()


def num_tpus() -> int:
    return len([d for d in jax.devices() if d.platform != "cpu"])


def num_gpus() -> int:
    """Reference ``mx.context.num_gpus`` — counts accelerators here."""
    return num_tpus()
