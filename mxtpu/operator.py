"""mx.operator — Python custom operators (reference
``python/mxnet/operator.py`` over ``src/operator/custom/custom.cc``
[path cites — unverified]).

The reference ran CustomOp.forward/backward on a dedicated worker thread
pool with GIL handoff; here the host callback is ``jax.pure_callback``,
which makes user numpy code callable from inside jitted programs too —
gradients route through ``jax.custom_vjp`` into the user's
``backward``. The (newer) ``lib_api.h`` C .so path is replaced by the
same mechanism: any ctypes-wrapped native function works inside
forward/backward.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as onp

from . import ndarray as nd
from .base import MXNetError, dtype_np
from .ndarray import NDArray
from .ndarray.ndarray import apply_op
from .ndarray.ops import register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_REGISTRY: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """User op base (reference ``mx.operator.CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst: NDArray, req: str, src) -> None:
        if req in ("null",):
            return
        src_data = src._data if isinstance(src, NDArray) else \
            jnp.asarray(onp.asarray(src))
        if req == "add":
            dst._set_data(dst._data + src_data.astype(dst.dtype))
        else:                       # 'write' / 'inplace'
            dst._set_data(src_data.astype(dst.dtype).reshape(dst.shape))


class CustomOpProp:
    """Op metadata + factory (reference ``mx.operator.CustomOpProp``)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def infer_storage_type(self, in_stype):
        return in_stype, ["default"] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Register a CustomOpProp subclass under a name (reference
    ``mx.operator.register``); invoke with ``mx.nd.Custom(...,
    op_type=reg_name)``."""
    def deco(prop_cls: Type[CustomOpProp]):
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return deco


def get_all_registered() -> List[str]:
    return sorted(_REGISTRY)


def _make_custom(prop: CustomOpProp, n_in: int):
    """Build the custom_vjp'd jax function for one prop instance."""
    out_names = prop.list_outputs()
    n_out = len(out_names)

    def _shapes_dtypes(arrs):
        in_shapes = [list(a.shape) for a in arrs]
        in_dtypes = [onp.dtype(a.dtype) for a in arrs]
        _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
        _, out_dtypes, _ = prop.infer_type(in_dtypes)
        return ([tuple(s) for s in out_shapes], out_dtypes)

    def _run_forward(is_train, *raw):
        op = prop.create_operator(None, [list(r.shape) for r in raw],
                                  [onp.dtype(r.dtype) for r in raw])
        in_data = [nd.array(onp.asarray(r), dtype=r.dtype) for r in raw]
        out_shapes, out_dtypes = _shapes_dtypes(raw)
        out_data = [nd.zeros(s, dtype=d)
                    for s, d in zip(out_shapes, out_dtypes)]
        op.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        return tuple(o.asnumpy() for o in out_data)

    def _run_backward(*raw):
        # raw = out_grads + in_datas + out_datas
        ogs = raw[:n_out]
        ins = raw[n_out:n_out + n_in]
        outs = raw[n_out + n_in:]
        op = prop.create_operator(None, [list(r.shape) for r in ins],
                                  [onp.dtype(r.dtype) for r in ins])
        in_data = [nd.array(onp.asarray(r), dtype=r.dtype) for r in ins]
        out_data = [nd.array(onp.asarray(r), dtype=r.dtype) for r in outs]
        out_grad = [nd.array(onp.asarray(g), dtype=g.dtype) for g in ogs]
        in_grad = [nd.zeros(i.shape, dtype=i.dtype) for i in in_data]
        op.backward(["write"] * n_in, out_grad, in_data, out_data,
                    in_grad, [])
        return tuple(g.asnumpy() for g in in_grad)

    @jax.custom_vjp
    def fn(*xs):
        out_shapes, out_dtypes = _shapes_dtypes(xs)
        result_shape = tuple(
            jax.ShapeDtypeStruct(s, dtype_np(d))
            for s, d in zip(out_shapes, out_dtypes))
        return jax.pure_callback(
            lambda *r: _run_forward(False, *r), result_shape, *xs)

    def fn_fwd(*xs):
        out_shapes, out_dtypes = _shapes_dtypes(xs)
        result_shape = tuple(
            jax.ShapeDtypeStruct(s, dtype_np(d))
            for s, d in zip(out_shapes, out_dtypes))
        outs = jax.pure_callback(
            lambda *r: _run_forward(True, *r), result_shape, *xs)
        return outs, (xs, outs)

    def fn_bwd(res, gs):
        xs, outs = res
        in_struct = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                          for x in xs)
        grads = jax.pure_callback(_run_backward, in_struct,
                                  *(tuple(gs) + xs + tuple(outs)))
        return tuple(grads)

    fn.defvjp(fn_fwd, fn_bwd)
    return fn


def _eager_custom(prop: CustomOpProp, inputs, op_type: str):
    """Host-side execution with a hand-built tape node — the path that
    works on every backend (the axon TPU PJRT plugin has no host-
    callback support, so pure_callback is jit-trace-only). This mirrors
    the reference most closely anyway: CustomOp ran on a host worker
    thread with device↔host copies around it."""
    from . import autograd
    from .ndarray.ndarray import _parents_of

    n_in = len(inputs)
    n_out = len(prop.list_outputs())
    in_shapes = [list(a.shape) for a in inputs]
    in_dtypes = [onp.dtype(a.dtype) for a in inputs]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    _, out_dtypes, _ = prop.infer_type(in_dtypes)
    op = prop.create_operator(None, in_shapes, in_dtypes)
    dev = next(iter(inputs[0]._data.devices())) if n_in else None

    in_data = [nd.array(a.asnumpy(), dtype=a.dtype) for a in inputs]
    out_data = [nd.zeros(tuple(s), dtype=d)
                for s, d in zip(out_shapes, out_dtypes)]
    op.forward(autograd.is_training(), ["write"] * n_out, in_data,
               out_data, [])
    out_raw = [jax.device_put(o.asnumpy(), dev) if dev is not None
               else o._data for o in out_data]

    parents = _parents_of(list(inputs))
    node = None
    if autograd.is_recording() and any(p is not None for p in parents):
        def vjp_fn(cot):
            cots = cot if isinstance(cot, tuple) else (cot,)
            out_grad = [nd.array(onp.asarray(c)) for c in cots]
            in_grad = [nd.zeros(i.shape, dtype=i.dtype) for i in in_data]
            op.backward(["write"] * n_in, out_grad, in_data, out_data,
                        in_grad, [])
            return tuple(jax.device_put(g.asnumpy(), dev)
                         if dev is not None else g._data for g in in_grad)

        avals = [(tuple(s), dtype_np(d))
                 for s, d in zip(out_shapes, out_dtypes)]
        node = autograd.Node(vjp_fn, parents, avals,
                             f"Custom[{op_type}]",
                             out_is_tuple=n_out > 1)
    results = []
    for i, o in enumerate(out_raw):
        r = NDArray(o)
        if node is not None:
            r._ag = (node, i)
        results.append(r)
    return results[0] if n_out == 1 else tuple(results)


@register_op("Custom")
def Custom(*inputs, op_type: Optional[str] = None, **kwargs):
    """Run a registered python CustomOp (reference ``mx.nd.Custom``)."""
    if op_type is None or op_type not in _REGISTRY:
        raise MXNetError(f"custom op {op_type!r} is not registered "
                         f"(known: {get_all_registered()})")
    prop = _REGISTRY[op_type](**{k: str(v) for k, v in kwargs.items()})
    tracing = any(isinstance(a._data, jax.core.Tracer) for a in inputs)
    if not tracing:
        return _eager_custom(prop, inputs, op_type)
    # under jit trace (hybridize): lower to pure_callback — supported on
    # CPU/GPU jit; the axon TPU plugin rejects host callbacks, so
    # hybridized Custom ops require eager mode there
    n_out = len(prop.list_outputs())
    raw = _make_custom(prop, len(inputs))
    if n_out == 1:
        return apply_op(lambda *xs: raw(*xs)[0], list(inputs),
                        f"Custom[{op_type}]")
    return apply_op(raw, list(inputs), f"Custom[{op_type}]", n_out=n_out)
