"""Lightweight span tracing: chrome://tracing-compatible events from
host-side code, alongside (never replacing) the ``jax.profiler`` XLA
trace.

A span measures HOST wall time between ``__enter__`` and ``__exit__``
— for dispatch-style code (the serve decode loop, the jitted train
step) that is host dispatch time, which is exactly the quantity the
overlapped-sync design cares about. Device time stays the XLA trace's
job; the two are complementary, not redundant.

Events accumulate in a bounded in-memory buffer (``trace_events()``,
dumped by :func:`dump_trace` as a Trace Event Format JSON array) and,
when ``MXTPU_TELEMETRY_TRACE_PATH`` is set, stream to that file as
JSONL — one ``{"name": ..., "ph": "X", ...}`` object per line, which
chrome://tracing and Perfetto both accept (their JSON importer
tolerates a missing enclosing array).

Nesting is tracked per thread: a span opened inside another span
carries ``args.depth`` and chrome's flame view nests them by
timestamp containment (same tid).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..base import env_int, env_str
from .flight import process_role

__all__ = ["span", "instant", "trace_events", "dump_trace",
           "clear_trace", "Span", "set_context_provider",
           "stream_path"]

_MAX_EVENTS = env_int(
    "MXTPU_TELEMETRY_TRACE_EVENTS", 100_000,
    "In-memory trace-event ring size; oldest events drop first.")

_lock = threading.Lock()
_events: Deque[Dict[str, Any]] = deque(maxlen=max(1, _MAX_EVENTS))
_tls = threading.local()
_stream_file = None
_stream_failed = False

# the distributed-tracing hook (telemetry.distributed installs it):
# called per recorded event; a non-empty return (trace_id, span id,
# request baggage) is merged under the event's args, so every span a
# request's context is active for carries the request's trace identity
# without tracing depending on the context layer
_ctx_provider = None


def set_context_provider(fn) -> None:
    """Install the callable that supplies the CURRENT request-scoped
    trace fields (``None``/falsy = no active context). One provider
    per process; ``telemetry.distributed`` owns it."""
    global _ctx_provider
    _ctx_provider = fn


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


# register the knobs once; the per-event check below is a bare dict
# lookup (this runs on every recorded event, under the trace lock)
env_str("MXTPU_TELEMETRY_TRACE_PATH", "",
        "Stream span trace events to this file as JSONL "
        "(chrome://tracing-compatible); empty disables streaming.")
env_str("MXTPU_TELEMETRY_TRACE_DIR", "",
        "Stream span trace events to a PER-PROCESS JSONL file "
        "mxtpu_trace_<role>_<pid>.jsonl under this directory — the "
        "multi-process serving topology's form of "
        "MXTPU_TELEMETRY_TRACE_PATH (one file per process, so a "
        "forked worker never clobbers its parent's stream; "
        "tools/diagnose.py timeline stitches them).")


# derived-path cache: (dir, role, pid) -> joined path. The env/role
# inputs are still read per call (tests and operators flip them
# live), but the join+format — the actual cost on the per-event path
# under the trace lock — reruns only when an input changes (fork,
# set_process_role, a new dir).
_derived_path: tuple = ("", "", 0, "")


def stream_path() -> str:
    """Where this process streams trace events right now (empty =
    streaming off). Inputs are read at WRITE time, so a process
    forked after import gets its own file instead of inheriting the
    parent's."""
    path = os.environ.get("MXTPU_TELEMETRY_TRACE_PATH", "")
    if path:
        return path
    d = os.environ.get("MXTPU_TELEMETRY_TRACE_DIR", "")
    if not d:
        return ""
    global _derived_path
    role, pid = process_role(), os.getpid()
    if _derived_path[:3] != (d, role, pid):
        _derived_path = (d, role, pid, os.path.join(
            d, f"mxtpu_trace_{role}_{pid}.jsonl"))
    return _derived_path[3]


def _stream(event: Dict[str, Any]) -> None:
    """Append one event to the stream target (lock held). A failing
    stream path degrades to in-memory-only, once, loudly."""
    global _stream_file, _stream_failed
    if _stream_failed:
        return
    path = stream_path()
    if not path:
        return
    try:
        if _stream_file is None or _stream_file.name != path:
            if _stream_file is not None:
                _stream_file.close()
            _stream_file = open(path, "a", buffering=1)
        # default=repr: span args are caller-supplied (numpy scalars,
        # arbitrary objects) — a telemetry write must never raise into
        # the instrumented code
        _stream_file.write(json.dumps(event, default=repr) + "\n")
    except Exception as e:
        _stream_failed = True
        import warnings
        warnings.warn(f"telemetry trace stream to {path!r} failed "
                      f"({e!r}); events stay in memory only",
                      RuntimeWarning)


def _record(event: Dict[str, Any]) -> None:
    if _ctx_provider is not None:
        ctx_fields = _ctx_provider()
        if ctx_fields:
            # explicit per-event args win over context baggage
            args = event.get("args")
            event["args"] = ({**ctx_fields, **args} if args
                             else dict(ctx_fields))
    with _lock:
        _events.append(event)
        _stream(event)


class Span:
    """One traced duration (context manager). ``duration_ms`` is
    populated on exit; ``args`` ride into the trace event verbatim."""

    def __init__(self, name: str, histogram=None, flight=None,
                 record: bool = True, **args: Any):
        self.name = name
        self.args = args
        self.duration_ms: Optional[float] = None
        self._histogram = histogram
        self._flight = flight
        self._record_event = record
        self._t0 = 0

    def __enter__(self) -> "Span":
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self.depth = depth
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_us()
        _tls.depth = max(0, getattr(_tls, "depth", 1) - 1)
        self.duration_ms = (t1 - self._t0) / 1000.0
        args = dict(self.args)
        if self.depth:
            args["depth"] = self.depth
        if self._record_event:
            _record({"name": self.name, "ph": "X", "ts": self._t0,
                     "dur": t1 - self._t0, "pid": os.getpid(),
                     "tid": threading.get_ident(), "args": args})
        if self._histogram is not None:
            self._histogram.observe(self.duration_ms)
        if self._flight is not None:
            self._flight.record("span", self.name,
                                dur_ms=round(self.duration_ms, 3),
                                **self.args)
        return False


def span(name: str, histogram=None, flight=None, **args: Any) -> Span:
    """``with telemetry.span("prefill", bucket=256): ...``"""
    return Span(name, histogram=histogram, flight=flight, **args)


def instant(name: str, **args: Any) -> None:
    """An instant event (chrome ph='i')."""
    _record({"name": name, "ph": "i", "ts": _now_us(), "s": "t",
             "pid": os.getpid(), "tid": threading.get_ident(),
             "args": args})


def trace_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def current_depth() -> int:
    """This thread's open-span nesting depth."""
    return getattr(_tls, "depth", 0)


def dump_trace(path: str) -> str:
    """Write the buffered events as a complete Trace Event Format JSON
    array (one event per line — both valid JSON and diffable)."""
    with _lock:
        events = list(_events)
    with open(path, "w") as f:
        f.write("[\n")
        f.write(",\n".join(json.dumps(e, default=repr)
                           for e in events))
        f.write("\n]\n")
    return path


def clear_trace() -> None:
    global _stream_failed
    with _lock:
        _events.clear()
        _stream_failed = False
