"""Lightweight span tracing: chrome://tracing-compatible events from
host-side code, alongside (never replacing) the ``jax.profiler`` XLA
trace.

A span measures HOST wall time between ``__enter__`` and ``__exit__``
— for dispatch-style code (the serve decode loop, the jitted train
step) that is host dispatch time, which is exactly the quantity the
overlapped-sync design cares about. Device time stays the XLA trace's
job; the two are complementary, not redundant.

Events accumulate in a bounded in-memory buffer (``trace_events()``,
dumped by :func:`dump_trace` as a Trace Event Format JSON array) and,
when ``MXTPU_TELEMETRY_TRACE_PATH`` is set, stream to that file as
JSONL — one ``{"name": ..., "ph": "X", ...}`` object per line, which
chrome://tracing and Perfetto both accept (their JSON importer
tolerates a missing enclosing array).

Nesting is tracked per thread: a span opened inside another span
carries ``args.depth`` and chrome's flame view nests them by
timestamp containment (same tid).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..base import env_int, env_str

__all__ = ["span", "instant", "trace_events", "dump_trace",
           "clear_trace", "Span"]

_MAX_EVENTS = env_int(
    "MXTPU_TELEMETRY_TRACE_EVENTS", 100_000,
    "In-memory trace-event ring size; oldest events drop first.")

_lock = threading.Lock()
_events: Deque[Dict[str, Any]] = deque(maxlen=max(1, _MAX_EVENTS))
_tls = threading.local()
_stream_file = None
_stream_failed = False


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


# register the knob once; the per-event check below is a bare dict
# lookup (this runs on every recorded event, under the trace lock)
env_str("MXTPU_TELEMETRY_TRACE_PATH", "",
        "Stream span trace events to this file as JSONL "
        "(chrome://tracing-compatible); empty disables streaming.")


def _stream(event: Dict[str, Any]) -> None:
    """Append one event to MXTPU_TELEMETRY_TRACE_PATH (lock held). A
    failing stream path degrades to in-memory-only, once, loudly."""
    global _stream_file, _stream_failed
    if _stream_failed:
        return
    path = os.environ.get("MXTPU_TELEMETRY_TRACE_PATH", "")
    if not path:
        return
    try:
        if _stream_file is None or _stream_file.name != path:
            if _stream_file is not None:
                _stream_file.close()
            _stream_file = open(path, "a", buffering=1)
        # default=repr: span args are caller-supplied (numpy scalars,
        # arbitrary objects) — a telemetry write must never raise into
        # the instrumented code
        _stream_file.write(json.dumps(event, default=repr) + "\n")
    except Exception as e:
        _stream_failed = True
        import warnings
        warnings.warn(f"telemetry trace stream to {path!r} failed "
                      f"({e!r}); events stay in memory only",
                      RuntimeWarning)


def _record(event: Dict[str, Any]) -> None:
    with _lock:
        _events.append(event)
        _stream(event)


class Span:
    """One traced duration (context manager). ``duration_ms`` is
    populated on exit; ``args`` ride into the trace event verbatim."""

    def __init__(self, name: str, histogram=None, flight=None,
                 record: bool = True, **args: Any):
        self.name = name
        self.args = args
        self.duration_ms: Optional[float] = None
        self._histogram = histogram
        self._flight = flight
        self._record_event = record
        self._t0 = 0

    def __enter__(self) -> "Span":
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self.depth = depth
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_us()
        _tls.depth = max(0, getattr(_tls, "depth", 1) - 1)
        self.duration_ms = (t1 - self._t0) / 1000.0
        args = dict(self.args)
        if self.depth:
            args["depth"] = self.depth
        if self._record_event:
            _record({"name": self.name, "ph": "X", "ts": self._t0,
                     "dur": t1 - self._t0, "pid": os.getpid(),
                     "tid": threading.get_ident(), "args": args})
        if self._histogram is not None:
            self._histogram.observe(self.duration_ms)
        if self._flight is not None:
            self._flight.record("span", self.name,
                                dur_ms=round(self.duration_ms, 3),
                                **self.args)
        return False


def span(name: str, histogram=None, flight=None, **args: Any) -> Span:
    """``with telemetry.span("prefill", bucket=256): ...``"""
    return Span(name, histogram=histogram, flight=flight, **args)


def instant(name: str, **args: Any) -> None:
    """An instant event (chrome ph='i')."""
    _record({"name": name, "ph": "i", "ts": _now_us(), "s": "t",
             "pid": os.getpid(), "tid": threading.get_ident(),
             "args": args})


def trace_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def current_depth() -> int:
    """This thread's open-span nesting depth."""
    return getattr(_tls, "depth", 0)


def dump_trace(path: str) -> str:
    """Write the buffered events as a complete Trace Event Format JSON
    array (one event per line — both valid JSON and diffable)."""
    with _lock:
        events = list(_events)
    with open(path, "w") as f:
        f.write("[\n")
        f.write(",\n".join(json.dumps(e, default=repr)
                           for e in events))
        f.write("\n]\n")
    return path


def clear_trace() -> None:
    global _stream_failed
    with _lock:
        _events.clear()
        _stream_failed = False
