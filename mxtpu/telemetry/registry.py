"""Process-wide metrics registry: labelled Counter / Gauge / Histogram
with a Prometheus text exposition and a human summary table.

Design (the always-on half of docs/observability.md):

- **Instruments are plain classes** — a :class:`Counter` constructed
  directly always works, with no global state, so a subsystem that
  needs private resettable stats (``ServeEngine.latency_stats``) can
  hold its own instance.
- **The registry is the process-wide namespace**: ``counter(name,
  **labels)`` interns one child per (name, label set) and every call
  site sharing the name shares the child — the property that makes a
  counter a cross-subsystem fact instead of a local variable.
- **Thread safety**: every mutation takes the instrument's own lock
  (serve callback thread, kvstore server threads, prefetcher thread
  and the training loop all write concurrently). Reads for export take
  the same locks, so a dump is a consistent snapshot per instrument.
- **Histograms are fixed-bucket**: O(len(buckets)) memory forever, no
  unbounded sample lists (what ``ServeEngine``'s private p50/p99 lists
  were before this module). Percentiles come from linear interpolation
  inside the crossing bucket — an estimate, bounded by bucket width,
  monotone in q by construction.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "interval_percentile", "interval_over_fraction",
           "escape_label_value",
           "LATENCY_MS_BUCKETS", "BYTES_BUCKETS", "SECONDS_BUCKETS"]

# log-spaced defaults: ~1.6x per step keeps the interpolation error of
# a percentile estimate under ~30% across 6 decades at 32 buckets
LATENCY_MS_BUCKETS = tuple(
    round(b, 4) for b in (
        0.05, 0.1, 0.2, 0.4, 0.8, 1.5, 2.5, 4, 6, 10, 16, 25, 40, 65,
        100, 160, 250, 400, 650, 1000, 1600, 2500, 4000, 6500, 10000,
        16000, 25000))
BYTES_BUCKETS = tuple(4 ** i for i in range(2, 16))        # 16B .. 1GB
SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 180.0)


def interval_percentile(bounds, prev_counts: Optional[List[int]],
                        counts: List[int],
                        q: float = 99.0) -> Optional[float]:
    """Percentile of the observations that landed BETWEEN two
    cumulative-bucket snapshots (the same interpolation as
    :meth:`Histogram.percentile`, applied to the diff) — THE
    bucket-diff math every windowed consumer shares (the autoscaler's
    latency signal, the gateway's SLO gauges). ``None`` when there is
    no previous snapshot or the window is empty."""
    if prev_counts is None:
        return None
    d = [c - p for c, p in zip(counts, prev_counts)]
    total = sum(d)
    if total <= 0:
        return None
    target = q / 100.0 * total
    cum = 0.0
    upper = bounds[-1]
    for i, c in enumerate(d):
        if c == 0:
            continue
        lower = bounds[i - 1] if i > 0 else 0.0
        upper = bounds[i] if i < len(bounds) else bounds[-1]
        if cum + c >= target:
            frac = (target - cum) / c
            return lower + frac * (upper - lower)
        cum += c
    return upper


def interval_over_fraction(bounds, prev_counts: Optional[List[int]],
                           counts: List[int],
                           threshold: float) -> Optional[float]:
    """Fraction of the window's observations above ``threshold``
    (linear interpolation inside the crossing bucket; the +Inf tail
    counts fully once its lower edge is reached) — the violation rate
    an SLO burn-rate gauge divides by its error budget. ``None`` when
    the window is empty."""
    if prev_counts is None:
        return None
    d = [c - p for c, p in zip(counts, prev_counts)]
    total = sum(d)
    if total <= 0:
        return None
    over = 0.0
    for i, c in enumerate(d):
        if c == 0:
            continue
        lower = bounds[i - 1] if i > 0 else 0.0
        upper = bounds[i] if i < len(bounds) else None   # +Inf
        if lower >= threshold:
            over += c
        elif upper is None:
            over += c          # tail straddles: no width to interpolate
        elif upper > threshold:
            over += c * (upper - threshold) / (upper - lower)
    return over / total


def escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — exposition-grammar safety for caller-supplied labels
    (error strings, peer addresses)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(s: str) -> str:
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


class Counter:
    """Monotonic counter."""

    kind = "counter"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Set/inc/dec instantaneous value."""

    kind = "gauge"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram with percentile estimates.

    ``buckets`` are inclusive upper bounds; an implicit +Inf bucket
    catches the overflow tail (its percentile estimate clamps to the
    last finite bound — an honest floor, never an invented value).
    """

    kind = "histogram"

    def __init__(self, buckets: Sequence[float] = LATENCY_MS_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(b <= a for a, b in zip(bounds, bounds[1:])):
            raise ValueError("buckets must be non-empty and increasing")
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)      # +Inf tail
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        lo, hi = 0, len(self.bounds)
        while lo < hi:                               # first bound >= value
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self._counts[lo] += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        with self._lock:
            n = sum(self._counts)
            return self._sum / n if n else 0.0

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(bucket counts incl. +Inf, sum, total) under one lock —
        the consistent view exporters read."""
        with self._lock:
            counts = list(self._counts)
            return counts, self._sum, sum(counts)

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100]) by linear
        interpolation inside the bucket where the cumulative count
        crosses q; exact observed min/max clamp the ends."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"q must be in [0, 100], got {q}")
        counts, _, total = self.snapshot()
        if total == 0:
            return 0.0
        target = q / 100.0 * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lower = self.bounds[i - 1] if i > 0 else \
                min(self._min or 0.0, self.bounds[0])
            upper = self.bounds[i] if i < len(self.bounds) else \
                max(self._max or self.bounds[-1], self.bounds[-1])
            if cum + c >= target:
                frac = (target - cum) / c
                return lower + frac * (upper - lower)
            cum += c
        return upper                                  # numeric slack

    def interval_percentile(self, prev_counts: Optional[List[int]],
                            counts: Optional[List[int]] = None,
                            q: float = 99.0) -> Optional[float]:
        """Windowed percentile between two cumulative snapshots of
        THIS histogram (``counts=None`` snapshots now — callers that
        keep the window state pass the counts they stored). Delegates
        to the module-level :func:`interval_percentile` so the
        bucket-diff math exists exactly once."""
        if counts is None:
            counts, _, _ = self.snapshot()
        return interval_percentile(self.bounds, prev_counts, counts, q)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._sum = 0.0
            self._min = self._max = None


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Family:
    """One metric name: its kind, help text, and per-label children."""

    def __init__(self, name: str, kind: str, help: str,
                 buckets: Optional[Sequence[float]]):
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = buckets
        self.children: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def child(self, labels: Dict[str, Any]):
        key = _label_key(labels)
        c = self.children.get(key)
        if c is None:
            c = {"counter": Counter, "gauge": Gauge}[self.kind]() \
                if self.kind != "histogram" else \
                Histogram(self.buckets or LATENCY_MS_BUCKETS)
            self.children[key] = c
        return c


class MetricsRegistry:
    """The process-wide metric namespace (one instance per process via
    ``mxtpu.telemetry.registry()``; constructible directly in tests)."""

    def __init__(self, prefix: str = "mxtpu"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help, buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"not {kind}")
            if help and not fam.help:
                fam.help = help
            return fam

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        fam = self._family(name, "counter", help)
        with self._lock:
            return fam.child(labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        fam = self._family(name, "gauge", help)
        with self._lock:
            return fam.child(labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> Histogram:
        fam = self._family(name, "histogram", help, buckets)
        with self._lock:
            return fam.child(labels)

    # -- introspection ----------------------------------------------------
    def value(self, name: str, **labels) -> float:
        """Current value of a counter/gauge child (0.0 if absent) —
        the read side tests and ``bench.py`` metadata use."""
        with self._lock:
            fam = self._families.get(name)
            child = fam.children.get(_label_key(labels)) if fam else None
        if child is None:
            return 0.0
        return child.value

    def get(self, name: str, **labels):
        """The child instrument itself, or None."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam.children.get(_label_key(labels))

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def reset(self) -> None:
        """Zero every child in place. Handles held by call sites stay
        valid — reset is test isolation, not teardown."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            for child in list(fam.children.values()):
                child.reset()

    def snapshot_state(self) -> list:
        """A wire-safe structural dump — what a worker/replica process
        ships to the federating gateway over the framed RPC (values,
        not text: the merge stays exact instead of re-parsing floats).
        ``[(name, kind, help, [(labels, payload), ...]), ...]`` where
        ``labels`` is ``[(k, v), ...]`` and ``payload`` is a float
        (counter/gauge) or ``(bounds, counts, sum)`` (histogram)."""
        out = []
        for fam in self.families():
            with self._lock:
                children = list(fam.children.items())
            kids = []
            for key, child in sorted(children):
                labels = [(k, v) for k, v in key]
                if fam.kind == "histogram":
                    counts, total_sum, _ = child.snapshot()
                    kids.append((labels, (list(child.bounds),
                                          list(counts),
                                          float(total_sum))))
                else:
                    kids.append((labels, float(child.value)))
            out.append((fam.name, fam.kind, fam.help, kids))
        return out

    # -- exporters --------------------------------------------------------
    @staticmethod
    def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                    extra: str = "") -> str:
        parts = [f'{k}="{escape_label_value(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @staticmethod
    def _fmt_num(v: float) -> str:
        return repr(int(v)) if float(v).is_integer() else repr(float(v))

    def prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for fam in self.families():
            full = f"{self.prefix}_{fam.name}"
            if fam.help:
                lines.append(f"# HELP {full} "
                             f"{_escape_help(fam.help)}")
            lines.append(f"# TYPE {full} {fam.kind}")
            with self._lock:
                children = list(fam.children.items())
            for key, child in sorted(children):
                if fam.kind == "histogram":
                    counts, total_sum, total = child.snapshot()
                    cum = 0
                    for bound, c in zip(child.bounds, counts):
                        cum += c
                        lab = self._fmt_labels(key, f'le="{bound}"')
                        lines.append(f"{full}_bucket{lab} {cum}")
                    lab = self._fmt_labels(key, 'le="+Inf"')
                    lines.append(f"{full}_bucket{lab} {total}")
                    lab = self._fmt_labels(key)
                    lines.append(f"{full}_sum{lab} "
                                 f"{self._fmt_num(total_sum)}")
                    lines.append(f"{full}_count{lab} {total}")
                else:
                    lab = self._fmt_labels(key)
                    lines.append(
                        f"{full}{lab} {self._fmt_num(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def summary(self) -> str:
        """Human table: one row per child; histograms show
        count/mean/p50/p99."""
        rows: List[Tuple[str, str, str]] = []
        for fam in self.families():
            with self._lock:
                children = list(fam.children.items())
            for key, child in sorted(children):
                label = fam.name + self._fmt_labels(key)
                if fam.kind == "histogram":
                    n = child.count
                    stat = (f"n={n}  mean={child.mean:.3f}  "
                            f"p50={child.percentile(50):.3f}  "
                            f"p99={child.percentile(99):.3f}") if n \
                        else "n=0"
                else:
                    stat = self._fmt_num(child.value)
                rows.append((label, fam.kind, stat))
        if not rows:
            return "(no metrics recorded)"
        w = max(len(r[0]) for r in rows)
        out = [f"{'Metric':<{w}}  {'Type':<9}  Value",
               "-" * (w + 2 + 9 + 2 + 40)]
        for label, kind, stat in rows:
            out.append(f"{label:<{w}}  {kind:<9}  {stat}")
        return "\n".join(out)
