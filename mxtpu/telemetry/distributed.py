"""Distributed request tracing + fleet metrics federation (ISSUE 8
tentpole; docs/observability.md §"Distributed tracing & federation").

The PR 5 telemetry layer is process-local: a request that crosses the
gateway front door, a prefill worker, a KV handoff and — after a
replica crash — a second decode replica leaves N disconnected span
logs and N separate ``/metrics`` registries. This module is the glue
that makes them ONE system:

- :class:`TraceContext` — a Dapper-style request-scoped context
  (``trace_id``, the current hop's ``span_id``, and baggage: the
  gateway request id, seed, absolute deadline) minted at the front
  door and carried on every hop the serve tier already makes. The
  context is ACTIVATED per thread (:func:`use`); every span/instant
  the tracing layer records while a context is active carries its
  fields, so per-process trace JSONL files stitch into one
  chrome://tracing view of the request's whole life
  (``tools/diagnose.py timeline <rid>``). Crash re-dispatch continues
  the SAME trace — the ``gateway.redispatch`` span links the old and
  new replica explicitly.
- **wire propagation** — ``mxtpu.rpc.attach_context`` /
  ``split_context`` put the context in a VERSIONED header around any
  framed-RPC message (the disagg KV handoff uses it); old frames
  without the header still decode, old fields never move.
- :class:`RegistryServer` + :func:`federate_text` — Prometheus-style
  federation over the existing framed RPC: worker/kvstore/replica
  processes expose their registry as a structural snapshot
  (``MetricsRegistry.snapshot_state`` — values, not text, so the
  merge is exact), and the gateway's ``/metrics`` merges them with a
  ``process`` label per series plus aggregate series (counters
  summed, histogram buckets merged, gauges last-write in scrape
  order).
- :class:`SLOTracker` — derived SLO gauges over the same plumbing:
  interval p99 of TTFT and inter-token latency vs. their targets
  (``MXTPU_GATEWAY_SLO_TTFT_MS`` / ``_TOKEN_MS``) and a burn rate
  (violating fraction / error budget) that feeds ``/healthz``
  degraded status. The bucket-diff math is
  ``registry.interval_percentile`` — shared with the autoscaler, not
  a second copy.
"""
from __future__ import annotations

import os
import re
import socket
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, replace as _dc_replace
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from ..base import env_float, env_str
from . import tracing as _tracing
from .flight import process_role
from .registry import (MetricsRegistry, _escape_help,
                       interval_over_fraction, interval_percentile)

__all__ = ["TraceContext", "mint", "current", "use",
           "RegistryServer", "scrape_peer", "federate_text",
           "parse_prometheus", "SLOTracker"]

_HEX = re.compile(r"^[0-9a-f]{8,32}$")


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _global_registry() -> MetricsRegistry:
    import mxtpu.telemetry as _tm
    return _tm.registry()


def _default_secret() -> bytes:
    """The federation wire secret: MXTPU_GATEWAY_SECRET, the SAME
    knob both sides of the disagg KV channel already read — a
    secret-enabled deployment must not need a second secret (or
    silently lose federation because only one side signed)."""
    return env_str(
        "MXTPU_GATEWAY_SECRET", "",
        "Shared secret for the gateway KV-handoff channel and the "
        "metrics-federation scrape RPC (HMAC-SHA256 when set)."
    ).encode()


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class TraceContext:
    """One request's trace identity + baggage, carried on every hop.

    ``trace_id`` names the whole request across processes;
    ``span_id`` names the current hop segment (each hop that wants
    its own identity calls :meth:`child`, which also records the
    parent segment); baggage is the small set of request facts every
    hop needs without a lookup: the gateway request id (``rid``), the
    sampling ``seed``, and the ABSOLUTE deadline (0 = none) — enough
    for any process on the path to log, shed, or resume coherently.
    Immutable: hops derive children instead of mutating."""

    trace_id: str
    span_id: str
    rid: int = -1
    seed: int = 0
    deadline_abs: float = 0.0
    parent_id: str = ""

    def child(self) -> "TraceContext":
        """A new segment of the same trace (fresh span_id, this
        segment recorded as its parent) — one per hop: prefill job,
        re-dispatch, a peer process continuing the request."""
        return _dc_replace(self, span_id=_new_id(4),
                           parent_id=self.span_id)

    def fields(self) -> Dict[str, Any]:
        """What every recorded event carries while this context is
        active (merged under the event's args by the tracing layer)."""
        out = {"trace_id": self.trace_id, "span": self.span_id,
               "rid": self.rid}
        if self.parent_id:
            out["parent_span"] = self.parent_id
        return out

    # -- wire form (rpc.attach_context header payload) ---------------------
    def to_wire(self) -> tuple:
        return (self.trace_id, self.span_id, int(self.rid),
                int(self.seed), float(self.deadline_abs))

    @classmethod
    def from_wire(cls, t: Sequence[Any]) -> "TraceContext":
        """Tolerant decode: extra trailing fields from a NEWER sender
        are ignored, missing ones default — the versioned-header
        forward/backward story."""
        t = tuple(t)
        if len(t) < 2 or not isinstance(t[0], str) \
                or not isinstance(t[1], str):
            raise ValueError(f"not a trace-context tuple: {t!r}")
        return cls(trace_id=t[0], span_id=t[1],
                   rid=int(t[2]) if len(t) > 2 else -1,
                   seed=int(t[3]) if len(t) > 3 else 0,
                   deadline_abs=float(t[4]) if len(t) > 4 else 0.0)


def mint(rid: int = -1, seed: int = 0, deadline_abs: float = 0.0,
         trace_id: Optional[str] = None) -> TraceContext:
    """Mint a fresh trace at the front door. A caller-supplied
    ``trace_id`` (an upstream proxy's) is honored when it is plausible
    hex; anything else is replaced rather than letting arbitrary
    client bytes into every log line."""
    tid = (trace_id if trace_id and _HEX.match(str(trace_id).lower())
           else None)
    return TraceContext(
        trace_id=(str(tid).lower() if tid else _new_id(8)),
        span_id=_new_id(4), rid=int(rid), seed=int(seed),
        deadline_abs=float(deadline_abs or 0.0))


_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The thread's active context (None outside any request)."""
    return getattr(_tls, "ctx", None)


@contextmanager
def use(ctx: Optional[TraceContext]):
    """Activate ``ctx`` for this thread (None = no-op): every span or
    instant recorded inside carries the trace fields. Restores the
    previous context on exit, so engine threads that interleave many
    requests never leak one request's identity into another's
    events."""
    if ctx is None:
        yield
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def _provider() -> Optional[Dict[str, Any]]:
    ctx = getattr(_tls, "ctx", None)
    return ctx.fields() if ctx is not None else None


_tracing.set_context_provider(_provider)


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------
_SCRAPE_REQ = ("mxmetrics", 1)


class RegistryServer:
    """Expose a process's metrics registry over the framed RPC — the
    one-liner a worker/kvstore/replica process runs so the gateway's
    ``/metrics`` can federate it:

    ``srv = RegistryServer(port=0, process="prefill0")``

    Protocol: one frame ``("mxmetrics", 1)`` in, one frame
    ``("mxmetrics", 1, process, snapshot)`` out, connection reusable;
    the snapshot is ``MetricsRegistry.snapshot_state()`` (wire-safe
    values — the merge is exact, no text re-parsing). Same HMAC/frame
    discipline as every other mxtpu socket when ``secret`` is set."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 registry: Optional[MetricsRegistry] = None,
                 process: Optional[str] = None,
                 secret: Optional[bytes] = None):
        from .. import rpc
        self._rpc = rpc
        self.registry = registry
        self.process = process or process_role()
        # None -> the deployment's MXTPU_GATEWAY_SECRET, matching
        # what a federating gateway signs its scrapes with; b"" opts
        # out explicitly
        self._secret = (_default_secret() if secret is None
                        else secret)
        self._closing = False
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(8)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"mxtpu-metrics-{self.process}").start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rpc = self._rpc
        try:
            conn.settimeout(30.0)
            while not self._closing:
                msg, _ = rpc.recv_msg(conn, self._secret)
                if not (isinstance(msg, tuple) and len(msg) >= 2
                        and msg[0] == _SCRAPE_REQ[0]):
                    rpc.send_msg(conn, ("mxerr", "not a metrics "
                                        "scrape"), self._secret)
                    return
                reg = self.registry or _global_registry()
                rpc.send_msg(
                    conn, ("mxmetrics", 1, self.process,
                           reg.snapshot_state()), self._secret)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass


def scrape_peer(host: str, port: int, *,
                secret: Optional[bytes] = None,
                timeout: float = 5.0) -> Tuple[str, list]:
    """One scrape of a peer :class:`RegistryServer`; returns
    ``(process_name, snapshot)``. Connection per scrape — federation
    must survive peer restarts without connection bookkeeping.
    ``secret=None`` uses the deployment's MXTPU_GATEWAY_SECRET, like
    the server side."""
    from .. import rpc
    if secret is None:
        secret = _default_secret()
    sock = socket.create_connection((host, int(port)),
                                    timeout=timeout)
    try:
        sock.settimeout(timeout)
        rpc.send_msg(sock, _SCRAPE_REQ, secret)
        reply, _ = rpc.recv_msg(sock, secret)
    finally:
        sock.close()
    if not (isinstance(reply, tuple) and len(reply) == 4
            and reply[0] == "mxmetrics"):
        raise rpc.RPCProtocolError(
            f"peer is not an mxtpu metrics endpoint: "
            f"{str(reply)[:80]}")
    return str(reply[2]), list(reply[3])


def _label_key(labels, process: Optional[str] = None
               ) -> Tuple[Tuple[str, str], ...]:
    items = [(str(k), str(v)) for k, v in labels]
    if process is not None:
        items.append(("process", str(process)))
    return tuple(sorted(items))


# exposition formatting is registry.py's, shared — an escaping fix
# there must cover the federated rendering path too
_fmt_labels = MetricsRegistry._fmt_labels


def _emit_scalar(lines: List[str], full: str, key, value) -> None:
    lines.append(f"{full}{_fmt_labels(key)} "
                 f"{MetricsRegistry._fmt_num(value)}")


def _emit_hist(lines: List[str], full: str, key, payload) -> None:
    bounds, counts, total_sum = payload
    cum = 0
    for bound, c in zip(bounds, counts):
        cum += c
        extra = 'le="%s"' % bound
        lines.append(f"{full}_bucket{_fmt_labels(key, extra)} {cum}")
    total = sum(counts)
    inf_extra = 'le="+Inf"'
    lines.append(f"{full}_bucket{_fmt_labels(key, inf_extra)} "
                 f"{total}")
    lines.append(f"{full}_sum{_fmt_labels(key)} "
                 f"{MetricsRegistry._fmt_num(total_sum)}")
    lines.append(f"{full}_count{_fmt_labels(key)} {total}")


def federate_text(registry: Optional[MetricsRegistry],
                  peers: Sequence[Tuple[str, int]], *,
                  process: Optional[str] = None,
                  secret: Optional[bytes] = None,
                  timeout: float = 5.0,
                  prefix: str = "mxtpu") -> str:
    """The federated Prometheus exposition: the local registry plus
    every reachable peer, each series labelled with its ``process``,
    plus one AGGREGATE series per label set (no ``process`` label):
    counters summed, histogram buckets merged element-wise (identical
    bounds — mismatched bounds keep per-process series only), gauges
    last-write in scrape order (local first, then ``peers`` in listed
    order — peers are scraped CONCURRENTLY, one thread each, so the
    whole scrape is bounded by ONE ``timeout``, not timeout×dead
    peers). An unreachable peer is skipped and counted in
    ``federation_errors_total{peer}`` — a scrape must degrade, not
    fail, when one worker is mid-restart."""
    import mxtpu.telemetry as _tm
    reg = registry or _global_registry()
    results: List[Optional[Tuple[str, list]]] = [None] * len(peers)

    def _scrape(i: int, host: str, port: int) -> None:
        try:
            results[i] = scrape_peer(host, port, secret=secret,
                                     timeout=timeout)
        except Exception as e:
            _tm.counter("federation_errors_total",
                        "Peer scrapes that failed during /metrics "
                        "federation", peer=f"{host}:{port}").inc()
            _tm.flight().record("telemetry",
                                "federation_scrape_failed",
                                peer=f"{host}:{port}",
                                error=repr(e)[:120])

    threads = [threading.Thread(target=_scrape, args=(i, h, p),
                                daemon=True)
               for i, (h, p) in enumerate(peers)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout + 1.0
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    # positional collection keeps the documented last-write order
    # deterministic regardless of which peer answered first; a
    # thread still running past the deadline leaves None (skipped —
    # its own error path does the counting when it resolves)
    snaps: List[Tuple[str, list]] = [
        (process or process_role(), reg.snapshot_state())]
    snaps += [r for r in results if r is not None]
    # two peers launched with the same role (or colliding pid-derived
    # defaults) must not emit duplicate series — a real Prometheus
    # server rejects the WHOLE scrape on a duplicate timeseries, so
    # one mislabeled worker would silently kill fleet metrics.
    # Dedup deterministically: first keeps the bare role, repeats get
    # a positional suffix.
    seen_roles: Dict[str, int] = {}
    deduped: List[Tuple[str, list]] = []
    for proc, snap in snaps:
        n = seen_roles.get(proc, 0)
        seen_roles[proc] = n + 1
        deduped.append((proc if n == 0 else f"{proc}~{n}", snap))
    snaps = deduped

    fams: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for proc, snap in snaps:
        for name, kind, help_, kids in snap:
            fam = fams.get(name)
            if fam is None:
                fam = fams[name] = {"kind": kind, "help": help_,
                                    "procs": []}
                order.append(name)
            if fam["kind"] != kind:
                continue            # kind conflict: first writer wins
            if help_ and not fam["help"]:
                fam["help"] = help_
            fam["procs"].append((proc, kids))

    lines: List[str] = []
    for name in sorted(order):
        fam = fams[name]
        full = f"{prefix}_{name}"
        if fam["help"]:
            lines.append(f"# HELP {full} "
                         f"{_escape_help(fam['help'])}")
        lines.append(f"# TYPE {full} {fam['kind']}")
        # aggregate per bare label set, in scrape order
        agg: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        agg_order: List[Tuple[Tuple[str, str], ...]] = []
        for proc, kids in fam["procs"]:
            for labels, payload in kids:
                key = _label_key(labels)
                if key not in agg:
                    agg_order.append(key)
                if fam["kind"] == "counter":
                    prev = agg.get(key, 0.0)
                    agg[key] = (None if prev is None
                                else prev + float(payload))
                elif fam["kind"] == "gauge":
                    agg[key] = float(payload)      # last write wins
                else:
                    prev = agg.get(key)
                    if prev is None and key in agg:
                        continue                   # poisoned: bounds
                    #                                mismatch earlier
                    if prev is None:
                        bounds, counts, s = payload
                        agg[key] = (list(bounds), list(counts),
                                    float(s))
                    elif list(prev[0]) == list(payload[0]):
                        prev_counts = [a + b for a, b in
                                       zip(prev[1], payload[1])]
                        agg[key] = (prev[0], prev_counts,
                                    prev[2] + float(payload[2]))
                    else:
                        agg[key] = None            # bounds mismatch:
                        #                            no exact merge
        for key in sorted(agg_order):
            payload = agg[key]
            if payload is None:
                continue
            if fam["kind"] == "histogram":
                _emit_hist(lines, full, key, payload)
            else:
                _emit_scalar(lines, full, key, payload)
        # per-process series, process label added
        for proc, kids in fam["procs"]:
            for labels, payload in sorted(
                    kids, key=lambda lp: _label_key(lp[0])):
                key = _label_key(labels, process=proc)
                if fam["kind"] == "histogram":
                    _emit_hist(lines, full, key, payload)
                else:
                    _emit_scalar(lines, full, key, payload)
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# exposition parser (grammar tests; diagnose)
# ---------------------------------------------------------------------------
_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(?:\{{(.*)\}})? "
    r"(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|[+-]Inf|NaN)$")


def _parse_labels(body: str) -> Tuple[Tuple[str, str], ...]:
    out: List[Tuple[str, str]] = []
    i, n = 0, len(body)
    while i < n:
        m = re.match(rf"({_NAME_RE})=\"", body[i:])
        if not m:
            raise ValueError(f"bad label at ...{body[i:i+40]!r}")
        name = m.group(1)
        i += m.end()
        val: List[str] = []
        while True:
            if i >= n:
                raise ValueError("unterminated label value")
            c = body[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ValueError("dangling escape")
                esc = body[i + 1]
                val.append({"\\": "\\", '"': '"', "n": "\n"}.get(
                    esc, "\\" + esc))
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                val.append(c)
                i += 1
        out.append((name, "".join(val)))
        if i < n:
            if body[i] != ",":
                raise ValueError(
                    f"expected ',' between labels at "
                    f"...{body[i:i+40]!r}")
            i += 1
    return tuple(sorted(out))


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Strict text-format 0.0.4 parser — the federation grammar
    test's oracle (and a programmatic reader for diagnose). Raises
    ``ValueError`` on any malformed line. Returns ``{"types":
    {name: kind}, "help": {name: text}, "samples": {(name,
    sorted-label-tuple): value}}``."""
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary",
                    "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 4:
                raise ValueError(f"line {lineno}: bad HELP: {line!r}")
            helps[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue                       # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: bad sample: {line!r}")
        name, body, value = m.groups()
        labels = _parse_labels(body) if body else ()
        if (name, labels) in samples:
            # a duplicate timeseries makes a real Prometheus server
            # reject the whole scrape — the oracle must be as strict
            raise ValueError(
                f"line {lineno}: duplicate series {name}"
                f"{dict(labels)}")
        samples[(name, labels)] = float(value)
    return {"types": types, "help": helps, "samples": samples}


# ---------------------------------------------------------------------------
# SLO gauges + burn rate
# ---------------------------------------------------------------------------
class SLOTracker:
    """Derived serving SLO gauges over the registry histograms the
    serve tier already populates — no new instrumentation, just the
    windowed read:

    - ``gateway_slo_p99_ms{slo}``: interval p99 of the underlying
      histogram since the last tick (the shared
      ``registry.interval_percentile`` bucket-diff);
    - ``gateway_slo_target_ms{slo}``: the configured target;
    - ``gateway_slo_burn_rate{slo}``: fraction of the window's
      observations over target, divided by the error budget
      (``1 - q/100``) — the classic burn rate: ``1.0`` = consuming
      budget exactly as fast as allowed, above = on course to violate.

    SLOs: ``ttft`` over ``gateway_ttft_ms`` and ``token`` over
    ``serve_token_latency_ms``, enabled by their targets
    (``MXTPU_GATEWAY_SLO_TTFT_MS`` / ``MXTPU_GATEWAY_SLO_TOKEN_MS``;
    0 = off). Ticks are rate-limited to ``window_s`` so scrapes and
    the gateway maintenance loop share one stable window; ``/healthz``
    reports ``degraded`` while any burn rate exceeds the threshold
    (``MXTPU_GATEWAY_SLO_BURN``)."""

    METRICS = {"ttft": "gateway_ttft_ms",
               "token": "serve_token_latency_ms"}

    def __init__(self, targets: Dict[str, float], *, q: float = 99.0,
                 burn_threshold: float = 1.0, window_s: float = 10.0,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Optional[Callable[[], float]] = None,
                 instruments: Optional[Dict[str, Any]] = None,
                 labels: Optional[Dict[str, str]] = None):
        unknown = set(targets) - set(self.METRICS)
        if unknown:
            raise ValueError(f"unknown SLOs {sorted(unknown)}; "
                             f"known: {sorted(self.METRICS)}")
        self.targets = {k: float(v) for k, v in targets.items()
                        if v and v > 0}
        self.q = float(q)
        self.burn_threshold = float(burn_threshold)
        self.window_s = float(window_s)
        self._registry = registry
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._prev: Dict[str, List[int]] = {}
        self._last_tick: Optional[float] = None
        self._last: Dict[str, Dict[str, Optional[float]]] = {}
        # per-model gateways hand their OWN histogram children here
        # (e.g. gateway_ttft_ms{model=...}) instead of the registry's
        # unlabeled default, and label the derived gauges to match —
        # two models' trackers then coexist in one registry without
        # clobbering each other's gateway_slo_* series
        self._instruments = dict(instruments or {})
        labels = dict(labels or {})
        import mxtpu.telemetry as _tm
        self._g_p99 = {s: _tm.gauge(
            "gateway_slo_p99_ms",
            "Interval p99 of the SLO's latency histogram since the "
            "last SLO window tick", slo=s, **labels)
            for s in self.targets}
        self._g_target = {s: _tm.gauge(
            "gateway_slo_target_ms", "Configured SLO latency target",
            slo=s, **labels) for s in self.targets}
        self._g_burn = {s: _tm.gauge(
            "gateway_slo_burn_rate",
            "Fraction of the window's observations over target, "
            "divided by the error budget (1 - q/100); > 1 burns "
            "budget faster than allowed", slo=s, **labels)
            for s in self.targets}
        for s, t in self.targets.items():
            self._g_target[s].set(t)

    @classmethod
    def from_spec(cls, spec: Dict[str, float], *,
                  clock: Optional[Callable[[], float]] = None,
                  instruments: Optional[Dict[str, Any]] = None,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional["SLOTracker"]:
        """Explicit-targets constructor (per-model SLOs in a fleet —
        one process, many trackers, so the env singleton does not
        fit): ``{"ttft_ms": 200, "token_ms": 50, "burn": 1.0,
        "window_s": 10}``, zero/absent targets disabled. None when no
        target survives, mirroring :meth:`from_env`."""
        spec = dict(spec or {})
        targets = {k: v for k, v in
                   (("ttft", float(spec.pop("ttft_ms", 0.0))),
                    ("token", float(spec.pop("token_ms", 0.0))))
                   if v > 0}
        burn = float(spec.pop("burn", 1.0))
        window = float(spec.pop("window_s", 10.0))
        if spec:
            raise ValueError(f"unknown SLO spec keys {sorted(spec)}")
        if not targets:
            return None
        return cls(targets, burn_threshold=burn, window_s=window,
                   clock=clock, instruments=instruments,
                   labels=labels)

    @classmethod
    def from_env(cls, clock: Optional[Callable[[], float]] = None, *,
                 instruments: Optional[Dict[str, Any]] = None,
                 labels: Optional[Dict[str, str]] = None
                 ) -> Optional["SLOTracker"]:
        """The gateway's constructor path: None when no SLO target is
        configured (the tracker, its gauges and its /healthz input
        all stay absent)."""
        ttft = env_float(
            "MXTPU_GATEWAY_SLO_TTFT_MS", 0.0,
            "Target p99 time-to-first-token (ms) for the gateway SLO "
            "gauges + burn rate; 0 disables the ttft SLO.")
        token = env_float(
            "MXTPU_GATEWAY_SLO_TOKEN_MS", 0.0,
            "Target p99 inter-token latency (ms) for the gateway SLO "
            "gauges + burn rate; 0 disables the token SLO.")
        burn = env_float(
            "MXTPU_GATEWAY_SLO_BURN", 1.0,
            "Burn-rate threshold above which /healthz reports "
            "status=degraded (1.0 = consuming error budget exactly "
            "as fast as allowed).")
        window = env_float(
            "MXTPU_GATEWAY_SLO_WINDOW_S", 10.0,
            "Minimum SLO tick window (seconds): scrapes/maintenance "
            "passes inside the window reuse the last computed "
            "p99/burn instead of chopping it into noise.")
        targets = {k: v for k, v in
                   (("ttft", ttft), ("token", token)) if v > 0}
        if not targets:
            return None
        return cls(targets, burn_threshold=burn, window_s=window,
                   clock=clock, instruments=instruments,
                   labels=labels)

    def tick(self, force: bool = False) -> Dict[str, Dict[str, Any]]:
        """Advance the window if it is due (or ``force``) and return
        the per-SLO ``{"p99_ms", "burn", "target_ms"}`` snapshot."""
        reg = self._registry or _global_registry()
        with self._lock:
            now = self._clock()
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self.window_s):
                return {s: dict(v) for s, v in self._last.items()}
            self._last_tick = now
            out: Dict[str, Dict[str, Any]] = {}
            for slo, target in self.targets.items():
                h = (self._instruments.get(slo)
                     or reg.get(self.METRICS[slo]))
                p99 = burn = None
                if h is not None:
                    counts, _, _ = h.snapshot()
                    prev = self._prev.get(slo)
                    self._prev[slo] = counts
                    p99 = interval_percentile(h.bounds, prev, counts,
                                              self.q)
                    frac = interval_over_fraction(h.bounds, prev,
                                                  counts, target)
                    if frac is not None:
                        budget = max(1e-9, 1.0 - self.q / 100.0)
                        burn = frac / budget
                self._g_p99[slo].set(p99 if p99 is not None else 0.0)
                self._g_burn[slo].set(burn if burn is not None
                                      else 0.0)
                out[slo] = {"p99_ms": p99, "burn": burn,
                            "target_ms": target}
            self._last = out
            return {s: dict(v) for s, v in out.items()}

    @staticmethod
    def _breached(last: Dict[str, Dict[str, Any]],
                  threshold: float) -> bool:
        return any(v.get("burn") is not None
                   and v["burn"] > threshold for v in last.values())

    @property
    def breached(self) -> bool:
        """True while any SLO's last-computed burn rate exceeds the
        threshold — the /healthz degraded input."""
        with self._lock:
            return self._breached(self._last, self.burn_threshold)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            slos = {s: dict(v) for s, v in self._last.items()}
            breached = self._breached(self._last,
                                      self.burn_threshold)
        for s, t in self.targets.items():
            slos.setdefault(s, {"p99_ms": None, "burn": None,
                                "target_ms": t})
        return {"slos": slos, "burn_threshold": self.burn_threshold,
                "breached": breached}
