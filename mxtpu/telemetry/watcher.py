"""Recompile watcher: turn silent XLA recompilation into a counted,
attributed runtime event.

Two hooks, independent and complementary:

1. **Global compile listener** (:func:`install`) — registers a
   ``jax`` monitoring listener for backend-compile durations, so EVERY
   compilation in the process increments ``jax_compile_total`` and
   lands in the compile-seconds histogram + flight recorder. Cheap,
   process-wide, no per-call overhead.
2. **Per-program watcher** (:func:`watch`) — wraps one jitted callable
   and checks its jit-cache size around each call (the same
   ``_cache_size()`` counter the serve churn test gates on). When the
   cache grows, the call's abstract signature — shapes, dtypes and
   shardings of every argument leaf — is recorded as the *cache key*
   that caused the compile. Growth beyond ``expected`` increments
   ``recompile_total{fn=...}`` with the offending key in the flight
   recorder: the trimmed-vs-padded ``PartitionSpec`` class of bug
   (PR 4, found by bisection) now surfaces at runtime as an anomalous
   counter whose recorded keys differ only in their spec strings.

``watch`` deliberately refuses a callable without ``_cache_size`` —
a silent no-op watcher would make the no-retrace contract vacuously
true exactly when a retrace bug could hide (same policy as
``ServeEngine.compile_count``).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, List, Optional

from . import perfscope as _perfscope

__all__ = ["install", "watch", "WatchedFunction", "describe_args"]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_install_lock = threading.Lock()
_installed = False
_MAX_KEY_CHARS = 512


def install() -> bool:
    """Register the process-wide compile listener (idempotent).
    Returns True if the listener is active."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax._src import monitoring as _mon
        except Exception as e:                  # jax moved the API
            logging.getLogger(__name__).warning(
                "telemetry: jax monitoring unavailable (%r); global "
                "compile counting disabled (per-program watch() still "
                "works)", e)
            return False
        from . import _metrics, flight as _fl
        from .registry import SECONDS_BUCKETS as _SECONDS

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event != _COMPILE_EVENT:
                return
            try:
                # resolve the registry PER EVENT (compiles are rare):
                # capturing it at install time would freeze the no-op
                # registry forever if telemetry was disabled then
                m = _metrics()
                m.counter("jax_compile_total",
                          "Backend compilations observed process-wide "
                          "(jax monitoring listener)").inc()
                m.histogram("jax_compile_seconds",
                            "Backend compile durations",
                            buckets=_SECONDS).observe(duration)
                _fl().record("compile", "backend_compile",
                             dur_s=round(duration, 4))
            except Exception:       # a listener must never break jit
                pass

        _mon.register_event_duration_secs_listener(_on_duration)
        _installed = True
        return True


def _leaf_desc(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        r = repr(leaf)
        return r if len(r) <= 32 else r[:29] + "..."
    desc = f"{getattr(dtype, 'name', dtype)}{list(shape)}"
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        desc += f"@{spec}"
    return desc


def describe_args(args: tuple, kwargs: dict) -> str:
    """A stable human-readable cache key for a jit call: every leaf's
    shape/dtype (+ sharding spec when placed) in tree order. Two calls
    that hit different jit-cache entries describe differently — shape,
    dtype, OR sharding-spec drift all show up in the string."""
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    key = "(" + ", ".join(_leaf_desc(l) for l in leaves) + ")"
    if len(key) > _MAX_KEY_CHARS:
        import hashlib
        h = hashlib.sha1(key.encode()).hexdigest()[:12]
        key = key[:_MAX_KEY_CHARS] + f"...#{h}"
    return key


class WatchedFunction:
    """A jitted callable with compile attribution. Transparent:
    attributes (``_cache_size``, ``lower``, ...) delegate to the
    wrapped function, so existing jit-cache gates keep working."""

    def __init__(self, fn: Callable, name: str,
                 expected: Optional[int] = 1,
                 loop: Optional[str] = None):
        if not hasattr(fn, "_cache_size"):
            raise TypeError(
                f"watch() needs a jitted callable with _cache_size "
                f"(got {type(fn).__name__}) — a watcher that cannot "
                "see the cache cannot attribute recompiles")
        self._fn = fn
        self.name = name
        self.expected = expected
        self.compiles: List[str] = []       # cache key per compile
        if loop is not None:
            _perfscope.scope().set_loop(name, loop)

    def __call__(self, *args, **kwargs):
        fn = self._fn
        before = fn._cache_size()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        t1 = time.perf_counter()
        after = fn._cache_size()
        if after > before:
            self._on_compile(args, kwargs, after)
        # perfscope step accounting: inter-dispatch gaps drive the
        # live MFU/MBU/goodput gauges + the step-anomaly detector
        _perfscope.scope().on_call(self.name, t0, t1)
        return out

    def _on_compile(self, args, kwargs, cache_size: int) -> None:
        from . import _metrics, flight as _fl
        # a fresh compiled variant: catalog its XLA cost model (the
        # lowering is still cached, so this is analysis, not a second
        # compile; profile_program never raises)
        _perfscope.scope().profile_program(self._fn, self.name,
                                           args, kwargs)
        key = describe_args(args, kwargs)
        self.compiles.append(key)
        m = _metrics()
        m.counter("compile_events_total",
                  "Compilations per watched program", fn=self.name).inc()
        if self.expected is not None and cache_size > self.expected:
            m.counter(
                "recompile_total",
                "Watched-program compilations beyond the expected "
                "count — an anomaly (shape churn, spec mismatch)",
                fn=self.name).inc()
            _fl().record("recompile", self.name, key=key,
                         cache_size=cache_size, expected=self.expected)
            logging.getLogger(__name__).warning(
                "telemetry: unexpected recompile of %s (cache size %d "
                "> expected %d) for signature %s", self.name,
                cache_size, self.expected, key)
        else:
            _fl().record("compile", self.name, key=key,
                         cache_size=cache_size)

    def __getattr__(self, name: str):
        return getattr(self.__dict__["_fn"], name)


def watch(fn: Callable, name: str,
          expected: Optional[int] = 1,
          loop: Optional[str] = None) -> WatchedFunction:
    """Wrap a jitted callable with compile attribution. ``expected``
    is the compile budget (cache entries) this program should ever
    need — 1 for a fixed-shape program; None disables the anomaly
    counter (compiles are still attributed). ``loop`` tags the
    program for perfscope's ``goodput_ratio{loop=...}`` gauge
    (``"train"`` / ``"serve"``)."""
    return WatchedFunction(fn, name, expected=expected, loop=loop)
