"""Perfscope — live roofline attribution, HBM ledger, step anomalies.

Three bench rounds of flat MFU showed the repo can *measure* that it
is slow but cannot say *where*: the numbers that explain a slow step
(per-program FLOPs/bytes from XLA's cost model, peak HBM, slot-bank
waste) were computed inside ``bench.py`` and thrown away. This module
makes them an always-on runtime layer on the PR 5/PR 8 telemetry
substrate:

- **program cost catalog** — :func:`profile_program` runs XLA
  ``cost_analysis()`` once per compiled variant of a watched program
  (``telemetry.watch`` calls it on every observed compile, so the
  train step, the fused step, and every serve program get it for
  free) and publishes ``mxtpu_program_flops``,
  ``mxtpu_program_bytes_accessed``, arithmetic intensity, and a
  roofline class (``compute_bound`` vs ``memory_bound`` at the
  device's FLOP/byte knee). Costs come from the CACHED lowering
  (``fn.lower`` after a call re-traces from the tracing cache — no
  second XLA compile); ``memory_analysis()`` needs a compiled object,
  so ``mxtpu_program_peak_hbm_bytes`` is published for AOT-compiled
  programs (:func:`program_costs`) always, and for watched jitted
  programs only under ``MXTPU_TELEMETRY_PERF_MEMORY=1`` (it forces a
  second full XLA compile per variant).
- **live MFU / MBU** — :meth:`PerfScope.on_call` keeps a rolling
  window of inter-dispatch gaps per program. Dispatch itself is async
  (host time is microseconds), but the gap between consecutive
  dispatches of a steady loop tracks the device step time: the loop
  is paced by the previous step's readback. Catalog flops/bytes over
  the rolling mean gap give ``mxtpu_mfu{program}`` and
  ``mxtpu_hbm_bw_util{program}``. The ratio math lives in ONE helper
  pair (:func:`mfu` / :func:`hbm_bw_util`) that ``bench.py`` also
  calls, so offline and live MFU cannot disagree by construction.
- **HBM ledger** — :class:`HBMLedger` accounts device-resident bytes
  by category (params / optimizer / kv_slot_bank / workspace),
  publishes ``mxtpu_hbm_ledger_bytes{category}`` +
  ``mxtpu_hbm_headroom_bytes``, and leaves an OOM-adjacent flight
  record when headroom first dips below
  ``MXTPU_TELEMETRY_PERF_HEADROOM_BYTES``. The KV byte helpers here
  (:func:`kv_slot_bank_bytes` / :func:`kv_live_bytes`) are the exact
  waste arithmetic ROADMAP item 1 (paged KV) is gated on.
- **step-anomaly detector** — per-program rolling median/MAD over the
  same gaps; a gap beyond ``median + k*MAD`` emits a ``perf.anomaly``
  instant, a flight record naming the program, and increments
  ``mxtpu_step_anomalies_total{program}``. Gaps longer than
  ``MXTPU_TELEMETRY_PERF_IDLE_S`` are treated as the loop being idle
  (a parked serve engine), not as a slow step: they reset the window
  instead of tripping the detector.

Goodput unification: :func:`goodput_gauge` is the ONE definition of
``mxtpu_goodput_ratio{loop=...}`` (the ``cancel_counter`` pattern) —
the elastic driver sets ``loop="elastic"`` from its committed-step
accounting, and programs registered with a loop (``watch(...,
loop="train"/"serve")``) get a step-pacing goodput (fraction of wall
the window spent at median pace) published automatically.

Everything here is exception-safe and honors the master
``MXTPU_TELEMETRY`` switch plus its own ``MXTPU_TELEMETRY_PERF`` knob:
a cost-analysis failure must never break a train or serve loop.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..base import env_bool, env_float, env_int

__all__ = [
    "DeviceSpec", "ProgramCost", "PerfScope", "HBMLedger",
    "device_spec", "spec_for", "mfu", "hbm_bw_util", "roofline_class",
    "profile_program", "program_costs", "on_call", "scope", "catalog",
    "ledger", "goodput_gauge", "tree_bytes", "kv_slot_bank_bytes",
    "kv_live_bytes", "reset",
]

_log = logging.getLogger(__name__)

# -- knobs (registered in docs/env_var.md via the base helpers) ------------
_PERF_ON = env_bool(
    "MXTPU_TELEMETRY_PERF", True,
    "Perfscope layer (program cost catalog, live MFU/MBU, step-anomaly "
    "detector). 0 disables it while leaving the rest of telemetry on.")
_WINDOW = env_int(
    "MXTPU_TELEMETRY_PERF_WINDOW", 64,
    "Rolling window (steps) for per-program MFU/MBU/goodput gauges and "
    "the anomaly detector's median/MAD.")
_ANOMALY_K = env_float(
    "MXTPU_TELEMETRY_PERF_ANOMALY_K", 8.0,
    "Step-anomaly threshold: a step gap beyond median + k*MAD of the "
    "rolling window trips mxtpu_step_anomalies_total + a flight record.")
_MIN_SAMPLES = env_int(
    "MXTPU_TELEMETRY_PERF_MIN_SAMPLES", 8,
    "Gaps required in a program's window before the anomaly detector "
    "arms (median/MAD over fewer steps is noise).")
_IDLE_S = env_float(
    "MXTPU_TELEMETRY_PERF_IDLE_S", 2.0,
    "A dispatch gap longer than this is the loop being IDLE (parked "
    "serve engine between requests), not a slow step: the program's "
    "rolling window resets instead of flagging an anomaly.")
_MEMORY = env_bool(
    "MXTPU_TELEMETRY_PERF_MEMORY", False,
    "Also run memory_analysis() (peak HBM) on watched jitted programs "
    "at compile time. Costs a SECOND full XLA compile per variant — "
    "AOT paths (bench gates) always get it for free via "
    "program_costs().")
_PEAK_FLOPS = env_float(
    "MXTPU_TELEMETRY_PERF_PEAK_FLOPS", 0.0,
    "Override the device's peak FLOP/s for MFU/roofline math "
    "(0 = use the built-in table keyed on device_kind).")
_PEAK_BW = env_float(
    "MXTPU_TELEMETRY_PERF_PEAK_BW", 0.0,
    "Override the device's peak HBM bytes/s for MBU/roofline math "
    "(0 = built-in table).")
_HBM_BYTES = env_float(
    "MXTPU_TELEMETRY_PERF_HBM_BYTES", 0.0,
    "Override the per-device HBM capacity for the ledger's headroom "
    "gauge (0 = device.memory_stats() when available, else the "
    "built-in table).")
_HEADROOM_BYTES = env_float(
    "MXTPU_TELEMETRY_PERF_HEADROOM_BYTES", 0.0,
    "When hbm_headroom_bytes first drops below this, record an "
    "OOM-adjacent flight event with the full ledger breakdown "
    "(0 = disabled; set ~1e9 on real chips).")


# -- device roofline specs -------------------------------------------------
@dataclass(frozen=True)
class DeviceSpec:
    """Per-chip peaks used for MFU/MBU and the roofline knee. The bf16
    matmul peak is the MFU convention every published number uses."""
    kind: str
    peak_flops: float        # bf16 FLOP/s, one chip
    peak_bw: float           # HBM bytes/s, one chip
    hbm_bytes: float         # HBM capacity, one chip

    @property
    def knee(self) -> float:
        """FLOP/byte where the roofline turns: programs with lower
        arithmetic intensity are memory-bound on this chip."""
        return self.peak_flops / self.peak_bw


# matched by substring of jax's device_kind, first hit wins; the CPU
# row is a nominal desktop-class roofline so CPU CI still classifies
# deterministically (override with the MXTPU_TELEMETRY_PERF_PEAK_*
# knobs for honest numbers on other hardware)
_SPECS: Tuple[Tuple[Tuple[str, ...], DeviceSpec], ...] = (
    (("v6e", "trillium"), DeviceSpec("v6e", 918e12, 1640e9, 32e9)),
    (("v5p",), DeviceSpec("v5p", 459e12, 2765e9, 95e9)),
    (("v5e", "v5 lite", "v5litepod"), DeviceSpec("v5e", 197e12,
                                                 819e9, 16e9)),
    (("v4",), DeviceSpec("v4", 275e12, 1228e9, 32e9)),
    (("cpu",), DeviceSpec("cpu", 5e11, 5e10, 16e9)),
)
_FALLBACK = DeviceSpec("unknown", 197e12, 819e9, 16e9)   # v5e numbers


def spec_for(kind: str) -> DeviceSpec:
    """The roofline spec for a device_kind string (e.g. ``"v5e"`` for
    bench gates that model v5e serving while running on CPU)."""
    k = str(kind).lower()
    for keys, spec in _SPECS:
        if any(key in k for key in keys):
            return spec
    return _FALLBACK


def _apply_overrides(spec: DeviceSpec) -> DeviceSpec:
    if not (_PEAK_FLOPS or _PEAK_BW or _HBM_BYTES):
        return spec
    return DeviceSpec(spec.kind,
                      _PEAK_FLOPS or spec.peak_flops,
                      _PEAK_BW or spec.peak_bw,
                      _HBM_BYTES or spec.hbm_bytes)


def device_spec() -> DeviceSpec:
    """The current process's device spec (first jax device), with the
    MXTPU_TELEMETRY_PERF_PEAK_* env overrides applied."""
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "cpu")
    except Exception:
        kind = "cpu"
    return _apply_overrides(spec_for(kind))


# -- the shared ratio helpers (bench.py + live gauges) ---------------------
def mfu(flops: float, seconds: float,
        peak_flops: Optional[float] = None) -> float:
    """Model FLOPs utilization: useful flops / (wall seconds x peak).
    THE one definition — ``bench.py`` passes its analytic flops and
    the v5e peak; the live gauges pass catalog flops and the local
    device peak. Pass ``peak_flops`` explicitly to pin the
    denominator (a gate record must not change meaning with the CI
    host's silicon)."""
    if seconds <= 0:
        return 0.0
    peak = device_spec().peak_flops if peak_flops is None else peak_flops
    return flops / seconds / peak if peak > 0 else 0.0


def hbm_bw_util(nbytes: float, seconds: float,
                peak_bw: Optional[float] = None) -> float:
    """Memory-bandwidth utilization: bytes accessed / (wall seconds x
    peak HBM bandwidth) — MBU, the serving-side twin of MFU."""
    if seconds <= 0:
        return 0.0
    peak = device_spec().peak_bw if peak_bw is None else peak_bw
    return nbytes / seconds / peak if peak > 0 else 0.0


def roofline_class(flops: float, bytes_accessed: float,
                   spec: Optional[DeviceSpec] = None) -> str:
    """``compute_bound`` iff arithmetic intensity (flops per byte
    accessed) is at or past the device's roofline knee."""
    sp = spec or device_spec()
    if bytes_accessed <= 0:
        return "compute_bound"
    return ("compute_bound" if flops / bytes_accessed >= sp.knee
            else "memory_bound")


# -- program cost catalog --------------------------------------------------
@dataclass
class ProgramCost:
    """One watched program's XLA cost-model summary (latest compiled
    variant; ``variants`` counts how many signatures were seen)."""
    name: str
    flops: float
    bytes_accessed: float
    transcendentals: float = 0.0
    argument_bytes: Optional[float] = None
    output_bytes: Optional[float] = None
    temp_bytes: Optional[float] = None
    peak_hbm_bytes: Optional[float] = None
    variants: int = 1
    spec: DeviceSpec = field(default_factory=device_spec)

    @property
    def intensity(self) -> float:
        return (self.flops / self.bytes_accessed
                if self.bytes_accessed > 0 else float("inf"))

    @property
    def klass(self) -> str:
        return roofline_class(self.flops, self.bytes_accessed, self.spec)


def _extract_costs(obj) -> Tuple[float, float, float]:
    """flops / bytes accessed / transcendentals from either AOT shape
    of ``cost_analysis()``: a Compiled returns a list of per-module
    dicts, a Lowered returns one flat dict."""
    ca = obj.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0),
            float(ca.get("transcendentals", 0.0) or 0.0))


def _extract_memory(compiled) -> Dict[str, float]:
    """memory_analysis() fields by portable names; peak falls back to
    args+out+temp when the backend doesn't report it (CPU)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    out: Dict[str, float] = {}
    for src, dst in (("argument_size_in_bytes", "argument_bytes"),
                     ("output_size_in_bytes", "output_bytes"),
                     ("temp_size_in_bytes", "temp_bytes"),
                     ("peak_memory_in_bytes", "peak_hbm_bytes")):
        v = getattr(mem, src, None)
        if v is not None:
            out[dst] = float(v)
    if "peak_hbm_bytes" not in out and {
            "argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
        out["peak_hbm_bytes"] = (out["argument_bytes"]
                                 + out["output_bytes"]
                                 + out["temp_bytes"])
    return out


def program_costs(compiled, name: Optional[str] = None,
                  spec: Optional[DeviceSpec] = None) -> Dict[str, Any]:
    """Cost + memory summary of an AOT ``Lowered``/``Compiled`` object
    as one plain dict — the shared helper the bench gate records read
    instead of ad-hoc inline ``memory_analysis()`` calls. With
    ``name``, the result also enters the live catalog (so an AOT
    bench's programs appear in the same roofline table). ``spec``
    pins the roofline knee (bench's v5e-story gates run on CPU)."""
    flops, nbytes, trans = _extract_costs(compiled)
    mem = _extract_memory(compiled) if hasattr(
        compiled, "memory_analysis") else {}
    sp = spec or device_spec()
    out = {"flops": flops, "bytes_accessed": nbytes,
           "transcendentals": trans,
           "roofline": roofline_class(flops, nbytes, sp), **mem}
    if name is not None:
        scope().register_cost(ProgramCost(
            name=name, flops=flops, bytes_accessed=nbytes,
            transcendentals=trans, spec=sp,
            argument_bytes=mem.get("argument_bytes"),
            output_bytes=mem.get("output_bytes"),
            temp_bytes=mem.get("temp_bytes"),
            peak_hbm_bytes=mem.get("peak_hbm_bytes")))
    return out


def tree_bytes(tree: Any) -> int:
    """Total array bytes in a pytree (the ledger's accounting unit;
    sharded arrays count their GLOBAL logical bytes)."""
    import jax
    return int(sum(getattr(l, "nbytes", 0)
                   for l in jax.tree_util.tree_leaves(tree)))


def kv_slot_bank_bytes(n_layers: int, n_kv_heads: int, head_dim: int,
                       max_slots: int, max_len: int,
                       itemsize: int) -> int:
    """Bytes the dense serve slot bank RESERVES: k and v of
    (L, max_slots, n_kv_heads, max_len, head_dim) each."""
    return 2 * n_layers * max_slots * n_kv_heads * max_len \
        * head_dim * itemsize


def kv_live_bytes(n_layers: int, n_kv_heads: int, head_dim: int,
                  lengths, itemsize: int) -> int:
    """Bytes live sequence prefixes actually COVER: the per-token KV
    row (k+v across layers/heads) times the summed live lengths. The
    reserved-minus-live gap is the dense bank's waste — the number
    ROADMAP item 1 (paged KV) is gated on."""
    per_token = 2 * n_layers * n_kv_heads * head_dim * itemsize
    return int(per_token * int(sum(int(x) for x in lengths)))


# -- HBM ledger ------------------------------------------------------------
class HBMLedger:
    """Per-process device-memory accounting. Entries are keyed
    (category, name) and last-write-wins, so a re-built trainer or a
    restarted engine replaces its own entry instead of double
    counting. Publishes ``hbm_ledger_bytes{category}`` and
    ``hbm_headroom_bytes`` on every change; the first dip below the
    headroom knob leaves an OOM-adjacent flight record with the full
    breakdown (edge-triggered — an OOM post-mortem needs one record,
    not a ring full of them)."""

    def __init__(self, headroom_bytes: Optional[float] = None):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], int] = {}
        self._low_latched = False
        self._headroom_knob = (_HEADROOM_BYTES if headroom_bytes is None
                               else float(headroom_bytes))

    def account(self, category: str, nbytes: int,
                name: str = "default") -> None:
        with self._lock:
            self._entries[(category, name)] = int(nbytes)
        self._publish()

    def account_tree(self, category: str, tree: Any,
                     name: str = "default") -> None:
        self.account(category, tree_bytes(tree), name=name)

    def release(self, category: str, name: str = "default") -> None:
        with self._lock:
            self._entries.pop((category, name), None)
        self._publish()

    def breakdown(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for (cat, _), n in self._entries.items():
                out[cat] = out.get(cat, 0) + n
            return out

    def total(self) -> int:
        return sum(self.breakdown().values())

    def capacity(self) -> float:
        """Per-process HBM budget: the device's own bytes_limit when
        it reports one (TPU), else the spec table / env override."""
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                return float(stats["bytes_limit"])
        except Exception:
            pass
        return device_spec().hbm_bytes

    def headroom(self) -> float:
        return self.capacity() - self.total()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._low_latched = False

    def _publish(self) -> None:
        try:
            from . import _metrics, flight as _fl
            m = _metrics()
            per_cat = self.breakdown()
            for cat, n in per_cat.items():
                m.gauge("hbm_ledger_bytes",
                        "Accounted device-resident bytes by category "
                        "(params/optimizer/kv_slot_bank/workspace)",
                        category=cat).set(n)
            head = self.headroom()
            m.gauge("hbm_headroom_bytes",
                    "HBM capacity minus every accounted allocation — "
                    "how close this process is to OOM").set(head)
            with self._lock:
                trip = (self._headroom_knob > 0
                        and head < self._headroom_knob
                        and not self._low_latched)
                if trip:
                    self._low_latched = True
                elif head >= self._headroom_knob:
                    self._low_latched = False
            if trip:
                _fl().record(
                    "perf", "hbm_headroom_low",
                    headroom_bytes=int(head),
                    capacity_bytes=int(self.capacity()),
                    threshold_bytes=int(self._headroom_knob),
                    **{f"bytes_{c}": n for c, n in per_cat.items()})
        except Exception:        # accounting must never break training
            pass


# -- rolling per-program step accounting -----------------------------------
class _Window:
    __slots__ = ("gaps", "last_end", "loop", "warned")

    def __init__(self, maxlen: int):
        self.gaps: deque = deque(maxlen=maxlen)
        self.last_end: Optional[float] = None
        self.loop: Optional[str] = None
        self.warned = False


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def goodput_gauge(loop: str):
    """``mxtpu_goodput_ratio{loop=...}`` — the ONE definition (the
    ``cancel_counter`` pattern): train, elastic, and serve goodput
    must scrape as one family, not three spellings."""
    from . import _metrics
    return _metrics().gauge(
        "goodput_ratio",
        "Useful fraction of wall time by loop (1.0 = every wall "
        "second was a committed step at nominal pace)", loop=loop)


class PerfScope:
    """The per-process attribution engine. The module-level singleton
    (:func:`scope`) is what ``telemetry.watch`` feeds; tests construct
    their own with tighter knobs. All public entry points swallow
    exceptions — perf attribution must never break the loop it
    measures."""

    def __init__(self, window: Optional[int] = None,
                 anomaly_k: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 idle_s: Optional[float] = None,
                 spec: Optional[DeviceSpec] = None):
        self.window = int(window or _WINDOW)
        self.anomaly_k = float(_ANOMALY_K if anomaly_k is None
                               else anomaly_k)
        self.min_samples = int(_MIN_SAMPLES if min_samples is None
                               else min_samples)
        self.idle_s = float(_IDLE_S if idle_s is None else idle_s)
        self._spec = spec
        self.catalog: Dict[str, ProgramCost] = {}
        self._windows: Dict[str, _Window] = {}
        self._loops: Dict[str, str] = {}
        self._published_class: Dict[str, str] = {}
        self.ledger = HBMLedger()
        self._lock = threading.Lock()

    # the knob gate: handles are NOT captured at construction (unlike
    # metric handles) because tests flip telemetry.enable() per test
    def _on(self) -> bool:
        from . import enabled
        return _PERF_ON and enabled()

    def spec(self) -> DeviceSpec:
        return self._spec or device_spec()

    # -- catalog ----------------------------------------------------------
    def set_loop(self, program: str, loop: Optional[str]) -> None:
        if loop:
            with self._lock:
                self._loops[program] = loop

    def register_cost(self, cost: ProgramCost) -> None:
        with self._lock:
            prev = self.catalog.get(cost.name)
            if prev is not None:
                cost.variants = prev.variants + 1
            self.catalog[cost.name] = cost
        self._publish_cost(cost)

    def profile_program(self, fn_or_compiled, name: str,
                        args: tuple = (), kwargs: Optional[dict] = None
                        ) -> Optional[ProgramCost]:
        """Catalog one program. Accepts an AOT ``Lowered``/``Compiled``
        (costs read directly) or a jitted callable + the call's args
        (``fn.lower`` re-traces from the tracing cache — cheap, and
        safe even when the args were just donated: lowering only
        reads shape/dtype/sharding metadata, which survives
        deletion)."""
        if not self._on():
            return None
        try:
            obj = fn_or_compiled
            if not hasattr(obj, "cost_analysis"):
                obj = obj.lower(*args, **(kwargs or {}))
            flops, nbytes, trans = _extract_costs(obj)
            mem = (_extract_memory(obj)
                   if hasattr(obj, "memory_analysis") else {})
            if not mem and _MEMORY and hasattr(obj, "compile"):
                # knob-gated: this is a SECOND full XLA compile
                mem = _extract_memory(obj.compile())
            cost = ProgramCost(
                name=name, flops=flops, bytes_accessed=nbytes,
                transcendentals=trans, spec=self.spec(),
                argument_bytes=mem.get("argument_bytes"),
                output_bytes=mem.get("output_bytes"),
                temp_bytes=mem.get("temp_bytes"),
                peak_hbm_bytes=mem.get("peak_hbm_bytes"))
            self.register_cost(cost)
            return cost
        except Exception as e:
            w = self._window(name)
            if not w.warned:
                w.warned = True
                _log.warning("perfscope: cost analysis failed for "
                             "%s (%r) — program stays uncataloged",
                             name, e)
            return None

    def _publish_cost(self, cost: ProgramCost) -> None:
        try:
            from . import _metrics
            m = _metrics()
            lbl = {"program": cost.name}
            m.gauge("program_flops",
                    "XLA cost-model FLOPs per execution of the "
                    "program (whole mesh)", **lbl).set(cost.flops)
            m.gauge("program_bytes_accessed",
                    "XLA cost-model bytes accessed per execution",
                    **lbl).set(cost.bytes_accessed)
            if cost.peak_hbm_bytes is not None:
                m.gauge("program_peak_hbm_bytes",
                        "Peak HBM during one execution "
                        "(memory_analysis)", **lbl).set(
                            cost.peak_hbm_bytes)
            if cost.bytes_accessed > 0:
                m.gauge("program_arithmetic_intensity",
                        "FLOPs per byte accessed — compare against "
                        "the device knee", **lbl).set(cost.intensity)
            klass = cost.klass
            prev = self._published_class.get(cost.name)
            help_ = ("1 for the program's side of the device's "
                     "FLOP/byte knee")
            if prev is not None and prev != klass:
                m.gauge("program_roofline", help_, program=cost.name,
                        **{"class": prev}).set(0)
            m.gauge("program_roofline", help_, program=cost.name,
                    **{"class": klass}).set(1)
            self._published_class[cost.name] = klass
        except Exception:
            pass

    # -- live step accounting ---------------------------------------------
    def _window(self, name: str) -> _Window:
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = self._windows[name] = _Window(self.window)
            return w

    def on_call(self, name: str, t_start: float, t_end: float) -> None:
        """One dispatch of a watched program: fold the inter-dispatch
        gap into the rolling window and refresh the program's MFU /
        MBU / goodput gauges + anomaly detector. Called on every
        train/serve step — must stay cheap and never raise."""
        if not self._on():
            return
        try:
            self._on_call(name, t_start, t_end)
        except Exception:
            pass

    def _on_call(self, name: str, t_start: float, t_end: float) -> None:
        w = self._window(name)
        last = w.last_end
        w.last_end = t_end
        if last is None:
            return
        gap = t_end - last
        if gap <= 0:
            return
        if gap > self.idle_s:
            w.gaps.clear()          # the loop was parked, not slow
            return
        from . import _metrics, flight as _fl, instant
        m = _metrics()
        m.counter("program_wall_ms_total",
                  "Wall time attributed to the program's dispatch "
                  "loop (sum of inter-dispatch gaps)",
                  program=name).inc(gap * 1e3)
        if len(w.gaps) >= self.min_samples:
            med = _median(list(w.gaps))
            mad = _median([abs(g - med) for g in w.gaps])
            # floor MAD at 2% of median: a perfectly steady window
            # would otherwise flag microsecond jitter
            thresh = med + self.anomaly_k * max(mad, 0.02 * med)
            if gap > thresh:
                m.counter("step_anomalies_total",
                          "Steps beyond median + k*MAD of the "
                          "program's rolling window",
                          program=name).inc()
                _fl().record("perf", "step_anomaly", program=name,
                             gap_ms=round(gap * 1e3, 3),
                             median_ms=round(med * 1e3, 3),
                             mad_ms=round(mad * 1e3, 3),
                             k=self.anomaly_k)
                instant("perf.anomaly", program=name,
                        gap_ms=round(gap * 1e3, 3))
        w.gaps.append(gap)
        self._refresh_gauges(name, w, m)

    def _refresh_gauges(self, name: str, w: _Window, m) -> None:
        if not w.gaps:
            return
        mean_gap = sum(w.gaps) / len(w.gaps)
        cost = self.catalog.get(name)
        if cost is not None and mean_gap > 0:
            import jax
            sp = self.spec()
            # catalog flops are whole-mesh, so the peak is too
            n_dev = max(1, jax.device_count())
            m.gauge("mfu",
                    "Live model-FLOPs utilization over the rolling "
                    "window (catalog flops / mean dispatch gap / "
                    "device peak)", program=name).set(
                        mfu(cost.flops, mean_gap,
                            peak_flops=sp.peak_flops * n_dev))
            m.gauge("hbm_bw_util",
                    "Live HBM-bandwidth utilization over the rolling "
                    "window (catalog bytes / mean dispatch gap / "
                    "device peak bandwidth)", program=name).set(
                        hbm_bw_util(cost.bytes_accessed, mean_gap,
                                    peak_bw=sp.peak_bw * n_dev))
        loop = self._loops.get(name)
        if loop:
            med = _median(list(w.gaps))
            total = sum(w.gaps)
            if total > 0:
                goodput_gauge(loop).set(
                    min(1.0, med * len(w.gaps) / total))

    # -- lifecycle --------------------------------------------------------
    def reset(self) -> None:
        """Drop rolling windows + ledger entries (test isolation; the
        catalog survives — program costs don't rot)."""
        with self._lock:
            self._windows.clear()
        self.ledger.clear()


# -- module singleton ------------------------------------------------------
_scope: Optional[PerfScope] = None
_scope_lock = threading.Lock()


def scope() -> PerfScope:
    global _scope
    if _scope is None:
        with _scope_lock:
            if _scope is None:
                _scope = PerfScope()
    return _scope


def profile_program(fn_or_compiled, name: str, args: tuple = (),
                    kwargs: Optional[dict] = None
                    ) -> Optional[ProgramCost]:
    return scope().profile_program(fn_or_compiled, name, args, kwargs)


def on_call(name: str, t_start: float, t_end: float) -> None:
    scope().on_call(name, t_start, t_end)


def catalog() -> Dict[str, ProgramCost]:
    return dict(scope().catalog)


def ledger() -> HBMLedger:
    return scope().ledger


def reset() -> None:
    scope().reset()
