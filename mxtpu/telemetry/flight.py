"""Crash flight recorder: a bounded ring buffer of recent runtime
events (spans, compiles, faults, explicit notes) that crash paths dump
to disk — the "what were the last N things this job did" answer that
a post-mortem needs when the metrics endpoint died with the process.

``checkpoint.PreemptionGuard`` dumps it on SIGTERM/SIGINT;
``tools/diagnose.py`` prints the live tail; anything can call
``telemetry.flight().dump()`` explicitly. The buffer is size-bounded
(``MXTPU_TELEMETRY_FLIGHT_SIZE``) so an always-on recorder costs a
fixed few hundred KB, never an OOM.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..base import atomic_write, env_int, env_str

__all__ = ["FlightRecorder", "default_flight_path", "process_role",
           "set_process_role"]

# the pid that imported this module — the parent of any later fork.
# A forked worker (prefill pool, DataLoader) inherits module state but
# has a NEW pid; path derivation compares against this so the child
# never dumps over the parent's file.
_IMPORT_PID = os.getpid()

_role_override: Optional[str] = None

env_str("MXTPU_TELEMETRY_PROCESS", "",
        "Role label of THIS process in the distributed telemetry "
        "surfaces (flight records, per-process trace files, the "
        "federated /metrics `process` label). Default: pid<pid>.")


def set_process_role(role: str) -> None:
    """Programmatic override of ``MXTPU_TELEMETRY_PROCESS`` (a serve
    worker naming itself after its pool role)."""
    global _role_override
    _role_override = str(role) if role else None


def process_role() -> str:
    """This process's role label — env/override read PER CALL, pid
    fallback derived per call, so a fork can never freeze the parent's
    identity into the child."""
    if _role_override:
        return _role_override
    return (os.environ.get("MXTPU_TELEMETRY_PROCESS", "")
            or f"pid{os.getpid()}")


def default_flight_path() -> str:
    """Where a crash dump lands: ``MXTPU_TELEMETRY_FLIGHT_PATH`` or a
    per-pid file under the system temp dir (predictable enough to find
    after a preemption, collision-free across ranks on one host).
    Derived at DUMP time: a process forked after import gets the env
    path suffixed with its own pid — without that, every worker in a
    forked pool would atomic-replace the same file and the last
    (least interesting) dump would win."""
    path = env_str(
        "MXTPU_TELEMETRY_FLIGHT_PATH", "",
        "Flight-recorder crash-dump file; default "
        "<tmpdir>/mxtpu_flight_<pid>.jsonl. A process forked after "
        "import dumps to <path>.<pid> so parallel dumps never "
        "clobber.")
    if not path:
        return os.path.join(
            tempfile.gettempdir(), f"mxtpu_flight_{os.getpid()}.jsonl")
    if os.getpid() != _IMPORT_PID:
        return f"{path}.{os.getpid()}"
    return path


class FlightRecorder:
    def __init__(self, maxlen: Optional[int] = None):
        if maxlen is None:
            maxlen = env_int(
                "MXTPU_TELEMETRY_FLIGHT_SIZE", 512,
                "Flight-recorder ring size (recent events kept for "
                "crash dumps).")
        # RLock, deliberately: PreemptionGuard records+dumps from a
        # SIGNAL HANDLER, which CPython runs on the main thread between
        # bytecodes — if the interrupted frame already holds this lock
        # (every span exit records), a non-reentrant lock would
        # deadlock the process on the exact path built to save it. A
        # non-main-thread holder only delays the handler (that thread
        # keeps running); the deque ops under the lock are single C
        # calls, so a re-entrant handler never sees torn state.
        self._lock = threading.RLock()
        self._events: Deque[Dict[str, Any]] = deque(
            maxlen=max(1, maxlen))

    def record(self, kind: str, name: str, **fields: Any) -> None:
        # tagged with the process role so stitched/collected dumps
        # from a multi-process serve tier stay attributable
        evt = {"t": round(time.time(), 6), "kind": kind, "name": name,
               "process": process_role()}
        evt.update(fields)
        with self._lock:
            self._events.append(evt)

    def tail(self, n: int = 20) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._events)
        return events[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: Optional[str] = None) -> str:
        """Write the ring as JSONL (atomic tmp+rename — a dump torn by
        the very crash it documents would be worse than none). Safe to
        call from a signal handler: any failure is swallowed after a
        best-effort stderr note, because the dump must never turn a
        clean preemption save into a crash."""
        path = path or default_flight_path()
        with self._lock:
            events = list(self._events)
        try:
            # default=repr: a numpy scalar in an event field must not
            # cost the crash dump its moment
            blob = "".join(json.dumps(e, default=repr) + "\n"
                           for e in events)
            atomic_write(path, blob.encode())
        except Exception as e:
            try:
                import sys
                sys.stderr.write(
                    f"mxtpu telemetry: flight dump to {path!r} failed: "
                    f"{e!r}\n")
            except Exception:
                pass
        return path

    def format_tail(self, n: int = 20) -> str:
        """Human-readable tail for diagnose.py."""
        events = self.tail(n)
        if not events:
            return "(flight recorder empty)"
        lines = []
        for e in events:
            extra = {k: v for k, v in e.items()
                     if k not in ("t", "kind", "name", "process")}
            ts = time.strftime("%H:%M:%S", time.localtime(e["t"]))
            lines.append(f"{ts}  {e['kind']:<9} {e['name']}"
                         + (f"  {extra}" if extra else ""))
        return "\n".join(lines)
