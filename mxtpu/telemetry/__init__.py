"""mxtpu.telemetry — unified runtime observability (docs/observability.md).

One process-wide, thread-safe layer with four pieces:

- **metrics registry** (labelled ``Counter``/``Gauge``/``Histogram``
  with fixed-bucket percentiles) — ``telemetry.counter("name").inc()``,
  exported as a Prometheus text dump (:func:`prometheus`) or a human
  table (:func:`summary`);
- **span tracing** — ``with telemetry.span("prefill", bucket=256):``
  emits chrome://tracing-compatible events alongside the XLA trace
  ``mx.profiler`` owns (host dispatch time here, device time there);
- **flight recorder** — a bounded ring of recent events that
  ``PreemptionGuard``/crash paths dump to disk (:func:`flight`);
- **recompile watcher** — every backend compilation is counted
  process-wide, and :func:`watch`-wrapped programs attribute each
  compile to its cache key, so an anomalous ``recompile_total`` points
  at the offending signature instead of a bisection session.

Enabled by default; ``MXTPU_TELEMETRY=0`` turns every recording call
into a no-op (handles created while disabled never record — the knob
is read when a handle is created, keeping the hot path branch-free).
The instrument classes themselves (``telemetry.Histogram()`` etc.)
always work when constructed directly — subsystems use them for
private resettable stats regardless of the global knob.

Instrumented out of the box: ``mxtpu.serve.ServeEngine`` (queue/slots/
admission/latency/spans), the sharded train step + ``DevicePrefetcher``
+ ``Speedometer`` (step-time split), and the ``kvstore`` client/server
(retries, dedups, reconnects, snapshot timing, frame sizes).
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..base import env_bool
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       escape_label_value, interval_percentile,
                       BYTES_BUCKETS, LATENCY_MS_BUCKETS,
                       SECONDS_BUCKETS)
from .flight import (FlightRecorder, default_flight_path,
                     process_role, set_process_role)
from . import tracing as _tracing
from .tracing import (Span, clear_trace, current_depth, dump_trace,
                      trace_events)
from . import perfscope
from .perfscope import goodput_gauge, profile_program
from .watcher import WatchedFunction, describe_args, watch
from .watcher import install as install_compile_listener

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "FlightRecorder", "Span", "WatchedFunction", "TraceContext",
    "RegistryServer", "SLOTracker",
    "counter", "gauge", "histogram", "span", "span_factory", "instant",
    "registry", "flight", "enabled", "enable", "reset",
    "prometheus", "summary", "dump_trace", "trace_events",
    "clear_trace", "current_depth", "describe_args", "watch",
    "install_compile_listener", "default_flight_path",
    "process_role", "set_process_role", "escape_label_value",
    "interval_percentile", "federate_text", "parse_prometheus",
    "distributed", "perfscope", "profile_program", "goodput_gauge",
    "LATENCY_MS_BUCKETS", "BYTES_BUCKETS", "SECONDS_BUCKETS",
]

class _GuardedFlight(FlightRecorder):
    """The process singleton: honors the MXTPU_TELEMETRY kill switch
    dynamically (unlike metric handles, flight callers hold the
    singleton long-term, so the check belongs at record time). A
    directly-constructed FlightRecorder is never gated."""

    def record(self, kind, name, **fields):
        if _enabled:
            super().record(kind, name, **fields)


_REGISTRY = MetricsRegistry()
_FLIGHT = _GuardedFlight()
_enabled = env_bool(
    "MXTPU_TELEMETRY", True,
    "Master switch for the runtime telemetry layer (metrics, spans, "
    "flight recorder). 0 disables all recording.")


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Runtime override of MXTPU_TELEMETRY (tests; emergency off
    switch). Affects handles created AFTER the call."""
    global _enabled
    _enabled = bool(on)


def registry() -> MetricsRegistry:
    """The process-wide registry (always real — exporters read it even
    when recording is disabled)."""
    return _REGISTRY


def flight() -> FlightRecorder:
    return _FLIGHT


# -- no-op handles (returned while disabled) -------------------------------
class _Noop:
    def inc(self, amount: float = 1.0) -> None: pass
    def dec(self, amount: float = 1.0) -> None: pass
    def set(self, value: float) -> None: pass
    def observe(self, value: float) -> None: pass
    value = 0.0
    count = 0


_NOOP = _Noop()


class _NoopRegistry:
    def counter(self, *a, **k): return _NOOP
    def gauge(self, *a, **k): return _NOOP
    def histogram(self, *a, **k): return _NOOP


_NOOP_REGISTRY = _NoopRegistry()


def _metrics():
    """Registry for WRITERS: the real one when enabled, no-ops when
    not (instrumentation sites call this once at handle creation)."""
    return _REGISTRY if _enabled else _NOOP_REGISTRY


def counter(name: str, help: str = "", **labels):
    return _metrics().counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels):
    return _metrics().gauge(name, help, **labels)


def histogram(name: str, help: str = "",
              buckets: Optional[Sequence[float]] = None, **labels):
    return _metrics().histogram(name, help, buckets=buckets, **labels)


def span(name: str, histogram_name: Optional[str] = None, **args):
    """A traced span. When telemetry is disabled this still returns a
    working ``Span`` timer but records nothing. ``histogram_name``
    additionally feeds the duration (ms) into that registry histogram;
    every span lands in the flight recorder."""
    return span_factory(name, histogram_name)(**args)


def span_factory(name: str, histogram_name: Optional[str] = None):
    """Pre-bind a span's registry histogram once and return a callable
    producing spans — the hot-path form (per decode step / train step,
    ``span()``'s per-call interning would take the registry lock every
    iteration)."""
    if not _enabled:
        return lambda **args: Span(name, record=False, **args)
    h = histogram(f"span_{histogram_name or name}_ms".replace(".", "_"),
                  f"Span durations: {name}") \
        if histogram_name is not False else None

    def make(**args):
        return Span(name, histogram=h, flight=_FLIGHT, **args)
    return make


def instant(name: str, **args) -> None:
    """An instant trace event (no-op while disabled)."""
    if _enabled:
        _tracing.instant(name, **args)


def prometheus() -> str:
    return _REGISTRY.prometheus()


def summary() -> str:
    return _REGISTRY.summary()


def reset() -> None:
    """Zero metrics, clear trace events and the flight ring (test
    isolation). Handles held by instrumentation stay valid."""
    _REGISTRY.reset()
    clear_trace()
    _FLIGHT.clear()
    # perfscope's rolling windows + ledger entries are test-visible
    # state too (the cost catalog survives — program costs don't rot)
    perfscope.reset()


# the distributed layer registers the tracing context provider at
# import; imported LAST — it reads this module's registry lazily
from . import distributed                                  # noqa: E402
from .distributed import (TraceContext, RegistryServer,    # noqa: E402
                          SLOTracker, federate_text,
                          parse_prometheus)
