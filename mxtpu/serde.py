"""Binary (de)serialization of NDArray containers — the ``.params`` format.

Rebuild of the reference's NDArray save/load (``src/ndarray/ndarray.cc``
NDArray::Save/Load + ``MXNDArraySave`` container in ``src/c_api/c_api.cc``
[path cite]), byte-compatible with the MXNet 1.x dense layout so model-zoo
weight files interchange:

    uint64 kMXAPINDArrayListMagic (0x112), uint64 reserved
    vector<NDArray>:  uint64 count, then per array:
        uint32 NDARRAY_V2_MAGIC (0xF993FAC9)
        int32  storage_type (-1 == dense/kDefaultStorage marker used here)
        TShape: uint32 ndim, uint32 dims[ndim]
        Context: int32 dev_type (1=cpu), int32 dev_id
        int32  type_flag (mshadow enum)
        raw data bytes
    vector<string> names: uint64 count, (uint64 len, bytes) each
"""
from __future__ import annotations

import struct
from typing import Dict, List, Union

import numpy as _np

from .base import MXNetError, dtype_np

_LIST_MAGIC = 0x112
_ND_MAGIC = 0xF993FAC9

# mshadow type flags (3rdparty/mshadow/mshadow/base.h)
_TYPE_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
              "int32": 4, "int8": 5, "int64": 6, "bool": 7, "bfloat16": 12}
_FLAG_TYPE = {v: k for k, v in _TYPE_FLAG.items()}


def _np_of(arr) -> _np.ndarray:
    from .ndarray.ndarray import NDArray
    if isinstance(arr, NDArray):
        return arr.asnumpy()
    return _np.asarray(arr)


def _write_sparse(out: List[bytes], arr) -> None:
    """Sparse entry: stype (1=row_sparse, 2=csr per the reference
    storage-type enum), shape, then aux arrays + data as dense blocks."""
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    out.append(struct.pack("<I", _ND_MAGIC))
    # 1001/1002 (not the reference's 1/2): our sparse block layout is
    # mxtpu-specific, so genuine MXNet 1.x sparse entries still get the
    # clean unsupported-format error below instead of a misparse
    stype = 1001 if isinstance(arr, RowSparseNDArray) else 1002
    out.append(struct.pack("<i", stype))
    out.append(struct.pack("<I", len(arr.shape)))
    out.append(struct.pack(f"<{len(arr.shape)}I", *arr.shape))
    out.append(struct.pack("<ii", 1, 0))  # cpu ctx
    if stype == 1001:
        auxes = [arr.indices.asnumpy().astype("int32")]
    else:
        auxes = [arr.indptr.asnumpy().astype("int32"),
                 arr.indices.asnumpy().astype("int32")]
    data = arr.data.asnumpy()
    out.append(struct.pack("<i", _TYPE_FLAG[_np.dtype(data.dtype).name]))
    out.append(struct.pack("<I", len(auxes)))
    for aux in auxes:
        out.append(struct.pack("<I", aux.shape[0]))
        out.append(aux.tobytes())
    out.append(struct.pack("<I", data.shape[0]))
    out.append(_np.ascontiguousarray(data).tobytes())


def _write_ndarray(out: List[bytes], a: _np.ndarray) -> None:
    out.append(struct.pack("<I", _ND_MAGIC))
    out.append(struct.pack("<i", 0))  # kDefaultStorage (dense)
    out.append(struct.pack("<I", a.ndim))
    out.append(struct.pack(f"<{a.ndim}I", *a.shape) if a.ndim else b"")
    out.append(struct.pack("<ii", 1, 0))  # cpu ctx
    name = _np.dtype(a.dtype).name
    if name not in _TYPE_FLAG:
        a = a.astype(_np.float32)
        name = "float32"
    out.append(struct.pack("<i", _TYPE_FLAG[name]))
    out.append(_np.ascontiguousarray(a).tobytes())


class _Reader:
    def __init__(self, buf: bytes):
        self.buf, self.pos = buf, 0

    def read(self, fmt: str):
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def read_bytes(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b


def _read_ndarray(r: _Reader) -> _np.ndarray:
    magic = r.read("I")
    if magic != _ND_MAGIC:
        raise MXNetError(f"bad NDArray magic {magic:#x} (legacy v0/v1 "
                         "formats not supported)")
    stype = r.read("i")
    # 0 == kDefaultStorage; accept -1 (kUndefinedStorage) written by early
    # versions of this codec
    if stype in (1, 2):
        raise MXNetError(
            "reference MXNet sparse .params entries (row_sparse/csr with "
            "the 1.x aux layout) are not supported; convert to dense or "
            "re-save with mxtpu")
    if stype not in (0, -1, 1001, 1002):
        raise MXNetError(f"unknown storage type {stype} in .params")
    if stype in (1001, 1002):
        return _read_sparse(r, stype)
    ndim = r.read("I")
    shape = tuple(r.read(f"{ndim}I")) if ndim > 1 else \
        ((r.read("I"),) if ndim == 1 else ())
    r.read("ii")  # ctx
    flag = r.read("i")
    dtype = dtype_np(_FLAG_TYPE[flag])
    n = int(_np.prod(shape)) if shape else 1
    data = _np.frombuffer(r.read_bytes(n * dtype.itemsize), dtype=dtype)
    return data.reshape(shape).copy()


def _read_sparse(r: _Reader, stype: int):
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    ndim = r.read("I")
    shape = tuple(r.read(f"{ndim}I")) if ndim > 1 else \
        ((r.read("I"),) if ndim == 1 else ())
    r.read("ii")  # ctx
    flag = r.read("i")
    dtype = dtype_np(_FLAG_TYPE[flag])
    n_aux = r.read("I")
    auxes = []
    for _ in range(n_aux):
        n = r.read("I")
        auxes.append(_np.frombuffer(r.read_bytes(n * 4),
                                    dtype=_np.int32).copy())
    n_data = r.read("I")
    if stype == 1001:
        row_shape = shape[1:]
        count = n_data
        nbytes = count * int(_np.prod(row_shape or (1,))) * dtype.itemsize
        data = _np.frombuffer(r.read_bytes(nbytes), dtype=dtype).reshape(
            (count,) + tuple(row_shape)).copy()
        return RowSparseNDArray(data, auxes[0], shape)
    data = _np.frombuffer(r.read_bytes(n_data * dtype.itemsize),
                          dtype=dtype).copy()
    return CSRNDArray(data, auxes[1], auxes[0], shape)


def save_ndarrays(fname: str, data) -> None:
    """``mx.nd.save``: data is NDArray, list[NDArray], or dict[str, NDArray]."""
    from .ndarray.ndarray import NDArray
    if isinstance(data, NDArray):
        arrays, names = [data], []
    elif isinstance(data, (list, tuple)):
        arrays, names = list(data), []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        raise TypeError(f"cannot save {type(data)}")
    out: List[bytes] = [struct.pack("<QQ", _LIST_MAGIC, 0),
                        struct.pack("<Q", len(arrays))]
    from .ndarray.sparse import BaseSparseNDArray
    for a in arrays:
        if isinstance(a, BaseSparseNDArray):
            _write_sparse(out, a)
        else:
            _write_ndarray(out, _np_of(a))
    out.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    with open(fname, "wb") as f:
        f.write(b"".join(out))


def load_ndarrays(fname: str):
    from .ndarray.ndarray import array as nd_array
    with open(fname, "rb") as f:
        r = _Reader(f.read())
    magic, _ = r.read("QQ")
    if magic != _LIST_MAGIC:
        raise MXNetError(f"invalid .params file (magic {magic:#x})")
    n = r.read("Q")
    from .ndarray.sparse import BaseSparseNDArray

    def _wrap(x):
        return x if isinstance(x, BaseSparseNDArray) else nd_array(x)
    arrays = [_wrap(_read_ndarray(r)) for _ in range(n)]
    n_names = r.read("Q")
    if n_names == 0:
        return arrays
    names = []
    for _ in range(n_names):
        ln = r.read("Q")
        names.append(r.read_bytes(ln).decode("utf-8"))
    return dict(zip(names, arrays))
