"""ResNet v1 — functional TPU-first core (BASELINE config 2, the
"ResNet-50 img/s/chip" headline metric).

The reference implements ResNet twice: symbolically
(``example/image-classification/symbols/resnet.py``) and as Gluon
blocks (``python/mxnet/gluon/model_zoo/vision/resnet.py``)
[path cites — unverified]. This is the TPU-native re-design:

- **NHWC layout** (channels-last) — what XLA:TPU tiles best onto the
  MXU conv units; the reference's NCHW was a cuDNN choice.
- **bf16 activations + f32 params/BN stats** — the v5e fast path.
- pure functions over a param pytree → composes with
  ``parallel.step.make_train_step`` (donated, dp/fsdp-sharded).
- BatchNorm in train mode normalizes with batch statistics and returns
  updated running stats as an auxiliary output (functional equivalent
  of the reference's mutable aux params).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ResNetConfig", "init_params", "init_state", "forward",
           "loss_fn", "CONFIGS"]

# layers-per-stage, bottleneck?
_SPECS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @property
    def stages(self) -> List[int]:
        return _SPECS[self.depth][0]

    @property
    def bottleneck(self) -> bool:
        return _SPECS[self.depth][1]


CONFIGS: Dict[str, ResNetConfig] = {
    "resnet18": ResNetConfig(depth=18),
    "resnet50": ResNetConfig(depth=50),
    "resnet101": ResNetConfig(depth=101),
    "tiny": ResNetConfig(depth=18, width=8, num_classes=10),
}


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------
def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = math.sqrt(2.0 / fan_in)          # He init (reference MSRAPrelu)
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * std


def _bn_params(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _block_channels(cfg: ResNetConfig, stage: int) -> Tuple[int, int]:
    mid = cfg.width * (2 ** stage)
    out = mid * 4 if cfg.bottleneck else mid
    return mid, out


def init_params(cfg: ResNetConfig, rng: Optional[jax.Array] = None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    d = cfg.param_dtype
    keys = iter(jax.random.split(rng, 256))
    p: Dict[str, Any] = {
        "stem_conv": _conv_init(next(keys), 7, 7, 3, cfg.width, d),
        "stem_bn": _bn_params(cfg.width, d),
    }
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk: Dict[str, Any] = {}
            if cfg.bottleneck:
                blk["conv1"] = _conv_init(next(keys), 1, 1, cin, mid, d)
                blk["bn1"] = _bn_params(mid, d)
                blk["conv2"] = _conv_init(next(keys), 3, 3, mid, mid, d)
                blk["bn2"] = _bn_params(mid, d)
                blk["conv3"] = _conv_init(next(keys), 1, 1, mid, cout, d)
                blk["bn3"] = _bn_params(cout, d)
            else:
                blk["conv1"] = _conv_init(next(keys), 3, 3, cin, mid, d)
                blk["bn1"] = _bn_params(mid, d)
                blk["conv2"] = _conv_init(next(keys), 3, 3, mid, cout, d)
                blk["bn2"] = _bn_params(cout, d)
            if stride != 1 or cin != cout:
                blk["down_conv"] = _conv_init(next(keys), 1, 1, cin, cout, d)
                blk["down_bn"] = _bn_params(cout, d)
            p[f"stage{s}_block{b}"] = blk
            cin = cout
    p["fc_w"] = jax.random.normal(
        next(keys), (cin, cfg.num_classes), d) / math.sqrt(cin)
    p["fc_b"] = jnp.zeros((cfg.num_classes,), d)
    return p


def init_state(cfg: ResNetConfig):
    """Running BN statistics (the reference's aux params)."""
    st: Dict[str, Any] = {"stem_bn": _bn_state(cfg.width)}
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = ({"bn1": _bn_state(mid), "bn2": _bn_state(mid),
                    "bn3": _bn_state(cout)} if cfg.bottleneck
                   else {"bn1": _bn_state(mid), "bn2": _bn_state(cout)})
            if stride != 1 or cin != cout:
                blk["down_bn"] = _bn_state(cout)
            st[f"stage{s}_block{b}"] = blk
            cin = cout
    return st


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _conv(x, w, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _tree_get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _apply_bn(cfg, x, p, st, train, updates, *path):
    x32 = x.astype(jnp.float32)
    if train:
        mean = x32.mean(axis=(0, 1, 2))
        var = x32.var(axis=(0, 1, 2))
        if updates is not None:
            m = cfg.bn_momentum
            s = _tree_get(st, path)
            updates[path] = {"mean": m * s["mean"] + (1 - m) * mean,
                             "var": m * s["var"] + (1 - m) * var}
    else:
        s = _tree_get(st, path)
        mean, var = s["mean"], s["var"]
    inv = lax.rsqrt(var + cfg.bn_eps)
    out = (x32 - mean) * inv * p["scale"].astype(jnp.float32) \
        + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def forward(cfg: ResNetConfig, params, x, state=None, train: bool = False):
    """x: (N, H, W, 3) → logits (N, classes) f32. In train mode returns
    (logits, new_state) with EMA-updated running BN stats."""
    if state is None:
        state = init_state(cfg)
    updates: Dict[Tuple[str, ...], Any] = {} if train else None
    x = x.astype(cfg.dtype)

    x = _conv(x, params["stem_conv"], stride=2)
    x = _apply_bn(cfg, x, params["stem_bn"], state, train, updates, "stem_bn")
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                          (1, 2, 2, 1), "SAME")

    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            name = f"stage{s}_block{b}"
            blk = params[name]
            sc = state[name]
            shortcut = x
            if "down_conv" in blk:
                shortcut = _conv(x, blk["down_conv"], stride=stride)
                shortcut = _apply_bn(cfg, shortcut, blk["down_bn"], state,
                                     train, updates, name, "down_bn")
            if cfg.bottleneck:
                h = jax.nn.relu(_apply_bn(cfg, _conv(x, blk["conv1"]),
                                          blk["bn1"], state, train, updates,
                                          name, "bn1"))
                h = jax.nn.relu(_apply_bn(cfg, _conv(h, blk["conv2"],
                                                     stride=stride),
                                          blk["bn2"], state, train, updates,
                                          name, "bn2"))
                h = _apply_bn(cfg, _conv(h, blk["conv3"]), blk["bn3"],
                              state, train, updates, name, "bn3")
            else:
                h = jax.nn.relu(_apply_bn(cfg, _conv(x, blk["conv1"],
                                                     stride=stride),
                                          blk["bn1"], state, train, updates,
                                          name, "bn1"))
                h = _apply_bn(cfg, _conv(h, blk["conv2"]), blk["bn2"],
                              state, train, updates, name, "bn2")
            x = jax.nn.relu(h + shortcut)
            cin = cout

    x = x.mean(axis=(1, 2))            # global average pool
    logits = (x.astype(jnp.float32) @ params["fc_w"].astype(jnp.float32)
              + params["fc_b"].astype(jnp.float32))
    if not train:
        return logits
    # fold flat updates back into a fresh nested state tree
    new_state = jax.tree.map(lambda a: a, state)   # rebuilds dict nodes
    for path, upd in updates.items():
        node = new_state
        for k in path[:-1]:
            node = node[k]
        node[path[-1]] = upd
    return logits, new_state


def loss_fn(cfg: ResNetConfig):
    """Softmax cross-entropy over {'image','label'} batches. Signature
    ``loss(params, bn_state, batch) -> (loss, new_bn_state)`` — use
    ``has_state=True`` in ``make_train_step`` so running BN stats
    accumulate across steps (init via
    ``init_state(..., model_state=resnet.init_state(cfg))``)."""

    def loss(params, state, batch):
        logits, new_state = forward(cfg, params, batch["image"], state,
                                    train=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, batch["label"][:, None].astype(jnp.int32), axis=-1)
        return nll.mean(), new_state
    return loss
