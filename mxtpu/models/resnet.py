"""ResNet v1 — functional TPU-first core (BASELINE config 2, the
"ResNet-50 img/s/chip" headline metric).

The reference implements ResNet twice: symbolically
(``example/image-classification/symbols/resnet.py``) and as Gluon
blocks (``python/mxnet/gluon/model_zoo/vision/resnet.py``)
[path cites — unverified]. This is the TPU-native re-design:

- **NHWC layout** (channels-last) — what XLA:TPU tiles best onto the
  MXU conv units; the reference's NCHW was a cuDNN choice.
- **bf16 activations + f32 params/BN stats** — the v5e fast path.
- pure functions over a param pytree → composes with
  ``parallel.step.make_train_step`` (donated, dp/fsdp-sharded).
- BatchNorm in train mode normalizes with batch statistics and returns
  updated running stats as an auxiliary output (functional equivalent
  of the reference's mutable aux params).
- **space-to-depth stem** (``ResNetConfig(stem="s2d")``): the 7×7/
  stride-2 stem conv rewritten as a 2×2 space-to-depth transform
  (N,224,224,3 → N,112,112,12) feeding a 4×4/stride-1 conv — the
  standard TPU countermeasure for the thin-C input conv (Ying et al.
  2018, *Image Classification at Supercomputer Scale*; Kumar et al.
  2019, MLPerf-0.6 on TPU-v3 pods). The stored parameter stays the
  standard (7,7,3,w) kernel; ``s2d_stem_kernel`` derives the exact
  equivalent (4,4,12,w) kernel inside the program (a pad + permute of
  a 12 KB tensor — nanoseconds next to the 6 TFLOP step), so the two
  stems share one checkpoint format, one optimizer state tree, and —
  because the transform is linear and the padded taps are structural
  zeros — the exact training trajectory, not just matching logits.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ResNetConfig", "init_params", "init_state", "forward",
           "loss_fn", "CONFIGS", "space_to_depth", "s2d_stem_kernel",
           "default_stem"]

# layers-per-stage, bottleneck?
_SPECS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    stem: str = "std"          # "std" (7×7/s2) | "s2d" (space-to-depth)

    @property
    def stages(self) -> List[int]:
        return _SPECS[self.depth][0]

    @property
    def bottleneck(self) -> bool:
        return _SPECS[self.depth][1]


CONFIGS: Dict[str, ResNetConfig] = {
    "resnet18": ResNetConfig(depth=18),
    "resnet50": ResNetConfig(depth=50),
    "resnet50_s2d": ResNetConfig(depth=50, stem="s2d"),
    "resnet101": ResNetConfig(depth=101),
    "tiny": ResNetConfig(depth=18, width=8, num_classes=10),
}


def default_stem() -> str:
    """Stem choice for benchmarks/examples: ``s2d`` on accelerator
    backends (the MXU wants the fattened input conv), ``std`` on CPU.
    ``MXTPU_RESNET_STEM=std|s2d`` overrides (docs/env_var.md)."""
    import os
    v = os.environ.get("MXTPU_RESNET_STEM", "auto").lower()
    if v in ("std", "s2d"):
        return v
    try:
        import jax as _jax
        return "s2d" if _jax.default_backend() not in ("cpu",) else "std"
    except Exception:
        return "std"


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------
def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = math.sqrt(2.0 / fan_in)          # He init (reference MSRAPrelu)
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * std


def _bn_params(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _block_channels(cfg: ResNetConfig, stage: int) -> Tuple[int, int]:
    mid = cfg.width * (2 ** stage)
    out = mid * 4 if cfg.bottleneck else mid
    return mid, out


def init_params(cfg: ResNetConfig, rng: Optional[jax.Array] = None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    d = cfg.param_dtype
    keys = iter(jax.random.split(rng, 256))
    p: Dict[str, Any] = {
        "stem_conv": _conv_init(next(keys), 7, 7, 3, cfg.width, d),
        "stem_bn": _bn_params(cfg.width, d),
    }
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk: Dict[str, Any] = {}
            if cfg.bottleneck:
                blk["conv1"] = _conv_init(next(keys), 1, 1, cin, mid, d)
                blk["bn1"] = _bn_params(mid, d)
                blk["conv2"] = _conv_init(next(keys), 3, 3, mid, mid, d)
                blk["bn2"] = _bn_params(mid, d)
                blk["conv3"] = _conv_init(next(keys), 1, 1, mid, cout, d)
                blk["bn3"] = _bn_params(cout, d)
            else:
                blk["conv1"] = _conv_init(next(keys), 3, 3, cin, mid, d)
                blk["bn1"] = _bn_params(mid, d)
                blk["conv2"] = _conv_init(next(keys), 3, 3, mid, cout, d)
                blk["bn2"] = _bn_params(cout, d)
            if stride != 1 or cin != cout:
                blk["down_conv"] = _conv_init(next(keys), 1, 1, cin, cout, d)
                blk["down_bn"] = _bn_params(cout, d)
            p[f"stage{s}_block{b}"] = blk
            cin = cout
    p["fc_w"] = jax.random.normal(
        next(keys), (cin, cfg.num_classes), d) / math.sqrt(cin)
    p["fc_b"] = jnp.zeros((cfg.num_classes,), d)
    return p


def init_state(cfg: ResNetConfig):
    """Running BN statistics (the reference's aux params)."""
    st: Dict[str, Any] = {"stem_bn": _bn_state(cfg.width)}
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = ({"bn1": _bn_state(mid), "bn2": _bn_state(mid),
                    "bn3": _bn_state(cout)} if cfg.bottleneck
                   else {"bn1": _bn_state(mid), "bn2": _bn_state(cout)})
            if stride != 1 or cin != cout:
                blk["down_bn"] = _bn_state(cout)
            st[f"stage{s}_block{b}"] = blk
            cin = cout
    return st


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _conv(x, w, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def space_to_depth(x, block: int = 2):
    """(N, H, W, C) → (N, H/b, W/b, b·b·C); flat channel order is
    (block_row, block_col, c) — ``s2d_stem_kernel`` depends on it."""
    n, h, w, c = x.shape
    b = block
    y = x.reshape(n, h // b, b, w // b, b, c)
    y = y.transpose(0, 1, 3, 2, 4, 5)
    return y.reshape(n, h // b, w // b, b * b * c)


def s2d_stem_kernel(k7):
    """EXACT rewrite of the (7,7,Cin,Cout) SAME/stride-2 stem kernel as
    the (4,4,4·Cin,Cout) kernel that consumes the 2×2 space-to-depth
    input with explicit padding (1,2)/(1,2) at stride 1.

    Derivation (even H; XLA SAME for k7/s2 pads lo=2, hi=3): output o
    reads original pixels 2o-2…2o+4. In block coordinates those span
    the 4 blocks o-1…o+2 — an 8-pixel window 2o-2…2o+5 whose last tap
    is phantom. So zero-pad the kernel 7→8 at the END, then regroup
    each spatial axis as (4 blocks × 2 sub-positions) and fold the sub-
    positions into the channel axis in ``space_to_depth``'s
    (row, col, c) order. The map is linear (permute + structural-zero
    pad), so gradients flow back to the 7×7 kernel unchanged and
    training trajectories match the standard stem exactly."""
    kh, kw, cin, cout = k7.shape
    if (kh, kw) != (7, 7):
        raise ValueError(f"s2d stem rewrite is for 7x7 kernels, got "
                         f"{(kh, kw)}")
    k8 = jnp.pad(k7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    k = k8.reshape(4, 2, 4, 2, cin, cout)       # (i, bh, j, bw, ci, co)
    k = k.transpose(0, 2, 1, 3, 4, 5)           # (i, j, bh, bw, ci, co)
    return k.reshape(4, 4, 4 * cin, cout)


def _stem(cfg, x, k7):
    if cfg.stem == "s2d":
        n, h, w, _ = x.shape
        if h % 2 or w % 2:
            raise ValueError(
                f"stem='s2d' needs even spatial dims, got {(h, w)}")
        return lax.conv_general_dilated(
            space_to_depth(x), s2d_stem_kernel(k7.astype(x.dtype)),
            (1, 1), [(1, 2), (1, 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return _conv(x, k7, stride=2)


def _tree_get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _apply_bn(cfg, x, p, st, train, updates, *path):
    x32 = x.astype(jnp.float32)
    if train:
        mean = x32.mean(axis=(0, 1, 2))
        var = x32.var(axis=(0, 1, 2))
        if updates is not None:
            m = cfg.bn_momentum
            s = _tree_get(st, path)
            updates[path] = {"mean": m * s["mean"] + (1 - m) * mean,
                             "var": m * s["var"] + (1 - m) * var}
    else:
        s = _tree_get(st, path)
        mean, var = s["mean"], s["var"]
    inv = lax.rsqrt(var + cfg.bn_eps)
    out = (x32 - mean) * inv * p["scale"].astype(jnp.float32) \
        + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def forward(cfg: ResNetConfig, params, x, state=None, train: bool = False):
    """x: (N, H, W, 3) → logits (N, classes) f32. In train mode returns
    (logits, new_state) with EMA-updated running BN stats."""
    if state is None:
        state = init_state(cfg)
    updates: Dict[Tuple[str, ...], Any] = {} if train else None
    x = x.astype(cfg.dtype)

    x = _stem(cfg, x, params["stem_conv"])
    x = _apply_bn(cfg, x, params["stem_bn"], state, train, updates, "stem_bn")
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                          (1, 2, 2, 1), "SAME")

    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stages):
        mid, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            name = f"stage{s}_block{b}"
            blk = params[name]
            sc = state[name]
            shortcut = x
            if "down_conv" in blk:
                shortcut = _conv(x, blk["down_conv"], stride=stride)
                shortcut = _apply_bn(cfg, shortcut, blk["down_bn"], state,
                                     train, updates, name, "down_bn")
            if cfg.bottleneck:
                h = jax.nn.relu(_apply_bn(cfg, _conv(x, blk["conv1"]),
                                          blk["bn1"], state, train, updates,
                                          name, "bn1"))
                h = jax.nn.relu(_apply_bn(cfg, _conv(h, blk["conv2"],
                                                     stride=stride),
                                          blk["bn2"], state, train, updates,
                                          name, "bn2"))
                h = _apply_bn(cfg, _conv(h, blk["conv3"]), blk["bn3"],
                              state, train, updates, name, "bn3")
            else:
                h = jax.nn.relu(_apply_bn(cfg, _conv(x, blk["conv1"],
                                                     stride=stride),
                                          blk["bn1"], state, train, updates,
                                          name, "bn1"))
                h = _apply_bn(cfg, _conv(h, blk["conv2"]), blk["bn2"],
                              state, train, updates, name, "bn2")
            x = jax.nn.relu(h + shortcut)
            cin = cout

    x = x.mean(axis=(1, 2))            # global average pool
    logits = (x.astype(jnp.float32) @ params["fc_w"].astype(jnp.float32)
              + params["fc_b"].astype(jnp.float32))
    if not train:
        return logits
    # fold flat updates back into a fresh nested state tree
    new_state = jax.tree.map(lambda a: a, state)   # rebuilds dict nodes
    for path, upd in updates.items():
        node = new_state
        for k in path[:-1]:
            node = node[k]
        node[path[-1]] = upd
    return logits, new_state


def loss_fn(cfg: ResNetConfig):
    """Softmax cross-entropy over {'image','label'} batches. Signature
    ``loss(params, bn_state, batch) -> (loss, new_bn_state)`` — use
    ``has_state=True`` in ``make_train_step`` so running BN stats
    accumulate across steps (init via
    ``init_state(..., model_state=resnet.init_state(cfg))``)."""

    def loss(params, state, batch):
        logits, new_state = forward(cfg, params, batch["image"], state,
                                    train=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, batch["label"][:, None].astype(jnp.int32), axis=-1)
        return nll.mean(), new_state
    return loss
