"""BERT — masked-LM pretraining family (BASELINE config 3; the
reference ecosystem ran BERT through GluonNLP over
``src/operator/contrib/transformer.cc`` interleaved-attention ops
[path cite — unverified]).

TPU-first functional design, mirroring mxtpu/models/llama.py:
- bf16 activations / f32 params, scan-over-layers (small HLO),
  optional remat,
- post-LN transformer encoder (original BERT), learned positions,
- MLM + NSP heads (MLM head reuses tied word embeddings, like the
  original),
- sharding rules: tp on attention/FFN projections, dp/fsdp on the
  batch — composes with parallel.step.make_train_step.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import dense_attention
from ..parallel.sharding import P, ShardingRules

__all__ = ["BertConfig", "CONFIGS", "init_params", "forward", "loss_fn",
           "sharding_rules"]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    scan_layers: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS: Dict[str, BertConfig] = {
    "tiny": BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                       hidden_dim=128, max_seq_len=64, remat=False),
    "bert_base": BertConfig(),
    "bert_large": BertConfig(dim=1024, n_layers=24, n_heads=16,
                             hidden_dim=4096),
}


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------
def _init_layer(key, cfg: BertConfig):
    d, h = cfg.dim, cfg.hidden_dim
    ks = jax.random.split(key, 4)
    dt = cfg.param_dtype

    def init(k, shape):
        # BERT's canonical truncated-normal(std 0.02, clipped ±2σ)
        # init, flat across layers (unlike llama's fan-in scaling)
        return jax.random.truncated_normal(k, -2.0, 2.0, shape, dt) * 0.02

    return {
        "qkv_w": init(ks[0], (d, 3 * d)),
        "qkv_b": jnp.zeros((3 * d,), dt),
        "attn_out_w": init(ks[1], (d, d)),
        "attn_out_b": jnp.zeros((d,), dt),
        "ln1_g": jnp.ones((d,), dt), "ln1_b": jnp.zeros((d,), dt),
        "ffn_in_w": init(ks[2], (d, h)),
        "ffn_in_b": jnp.zeros((h,), dt),
        "ffn_out_w": init(ks[3], (h, d)),
        "ffn_out_b": jnp.zeros((d,), dt),
        "ln2_g": jnp.ones((d,), dt), "ln2_b": jnp.zeros((d,), dt),
    }


def init_params(cfg: BertConfig, rng: Optional[jax.Array] = None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ks = jax.random.split(rng, 7)
    d = cfg.dim
    dt = cfg.param_dtype
    layers = [_init_layer(k, cfg)
              for k in jax.random.split(ks[0], cfg.n_layers)]
    if cfg.scan_layers:
        layer_params = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    else:
        layer_params = layers
    def _tn(k, shape):
        return jax.random.truncated_normal(k, -2.0, 2.0, shape, dt) * 0.02

    return {
        "tok_emb": _tn(ks[1], (cfg.vocab_size, d)),
        "pos_emb": _tn(ks[2], (cfg.max_seq_len, d)),
        "type_emb": _tn(ks[3], (cfg.type_vocab_size, d)),
        "emb_ln_g": jnp.ones((d,), dt), "emb_ln_b": jnp.zeros((d,), dt),
        "layers": layer_params,
        "pool_w": _tn(ks[4], (d, d)),
        "pool_b": jnp.zeros((d,), dt),
        "mlm_w": _tn(ks[5], (d, d)),
        "mlm_b": jnp.zeros((d,), dt),
        "mlm_ln_g": jnp.ones((d,), dt), "mlm_ln_b": jnp.zeros((d,), dt),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), dt),
        "nsp_w": _tn(ks[6], (d, 2)),
        "nsp_b": jnp.zeros((2,), dt),
    }


def sharding_rules(cfg: Optional[BertConfig] = None) -> ShardingRules:
    """tp over attention heads / FFN inner dim, fsdp over the first
    axis of big tables (same recipe as llama.sharding_rules).
    scan_layers (the default) stacks per-layer params with a leading
    layer axis, so the specs carry a leading None."""
    scan = cfg.scan_layers if cfg is not None else True
    return ShardingRules([
        (r".*tok_emb", P("fsdp", "tp")),
        (r".*pos_emb", P(None, "tp")),
        (r".*qkv_w", P(None, "fsdp", "tp") if scan else P("fsdp", "tp")),
        (r".*attn_out_w", P(None, "tp", "fsdp") if scan
         else P("tp", "fsdp")),
        (r".*ffn_in_w", P(None, "fsdp", "tp") if scan
         else P("fsdp", "tp")),
        (r".*ffn_out_w", P(None, "tp", "fsdp") if scan
         else P("tp", "fsdp")),
        (r".*mlm_w", P("fsdp", "tp")),
        (r".*", P()),
    ])


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _layer_norm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * g.astype(jnp.float32) +
            b.astype(jnp.float32)).astype(x.dtype)


def _encoder_layer(cfg: BertConfig, x, mask, lp):
    B, S, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    dt = cfg.dtype
    qkv = x @ lp["qkv_w"].astype(dt) + lp["qkv_b"].astype(dt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    # shared attention kernel (same masked-softmax semantics as the
    # blockwise/ring variants used by llama)
    ctx = dense_attention(q, k, v,
                          mask=(mask[:, None, None, :] > 0)).astype(dt)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, D)
    ctx = ctx @ lp["attn_out_w"].astype(dt) + lp["attn_out_b"].astype(dt)
    x = _layer_norm(x + ctx, lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
    h = jax.nn.gelu(x @ lp["ffn_in_w"].astype(dt) +
                    lp["ffn_in_b"].astype(dt), approximate=True)
    h = h @ lp["ffn_out_w"].astype(dt) + lp["ffn_out_b"].astype(dt)
    return _layer_norm(x + h, lp["ln2_g"], lp["ln2_b"], cfg.norm_eps)


def forward(cfg: BertConfig, params, tokens, token_types=None, mask=None):
    """tokens (B, S) int32 → (sequence_output (B,S,D) f32,
    pooled_output (B,D) f32)."""
    B, S = tokens.shape
    if S > cfg.max_seq_len:
        raise ValueError(
            f"sequence length {S} exceeds max_seq_len {cfg.max_seq_len}")
    dt = cfg.dtype
    if mask is None:
        mask = jnp.ones((B, S), jnp.float32)
    if token_types is None:
        token_types = jnp.zeros((B, S), jnp.int32)
    x = params["tok_emb"][tokens].astype(dt) + \
        params["pos_emb"][None, :S].astype(dt) + \
        params["type_emb"][token_types].astype(dt)
    x = _layer_norm(x, params["emb_ln_g"], params["emb_ln_b"],
                    cfg.norm_eps)

    def one_layer(x, lp):
        return _encoder_layer(cfg, x, mask, lp)

    if cfg.remat:
        one_layer = jax.checkpoint(one_layer)
    if cfg.scan_layers:
        def body(x, lp):
            return one_layer(x, lp), None
        x, _ = jax.lax.scan(body, x, params["layers"])
    else:
        for lp in params["layers"]:
            x = one_layer(x, lp)
    seq_out = x.astype(jnp.float32)
    pooled = jnp.tanh(seq_out[:, 0] @ params["pool_w"].astype(jnp.float32)
                      + params["pool_b"].astype(jnp.float32))
    return seq_out, pooled


def mlm_logits(cfg: BertConfig, params, seq_out):
    """Masked-LM head: transform + tied-embedding decode."""
    h = jax.nn.gelu(seq_out @ params["mlm_w"].astype(jnp.float32) +
                    params["mlm_b"].astype(jnp.float32), approximate=True)
    h = _layer_norm(h, params["mlm_ln_g"], params["mlm_ln_b"],
                    cfg.norm_eps)
    return h @ params["tok_emb"].astype(jnp.float32).T + \
        params["mlm_bias"].astype(jnp.float32)


def loss_fn(cfg: BertConfig):
    """Pretraining loss over batches {'tokens', 'mask', 'mlm_positions',
    'mlm_labels', 'mlm_weights'[, 'token_types', 'nsp_labels']}:
    MLM cross-entropy (+ NSP when labels present) — the reference-era
    BERT objective."""

    def loss(params, batch):
        seq_out, pooled = forward(cfg, params, batch["tokens"],
                                  batch.get("token_types"),
                                  batch["mask"])
        pos = batch["mlm_positions"]                 # (B, P) int32
        gathered = jnp.take_along_axis(
            seq_out, pos[..., None].astype(jnp.int32), axis=1)
        logits = mlm_logits(cfg, params, gathered)   # (B, P, V)
        logp = jax.nn.log_softmax(logits, axis=-1)
        lab = batch["mlm_labels"].astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        w = batch["mlm_weights"].astype(jnp.float32)
        mlm_loss = jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
        total = mlm_loss
        if "nsp_labels" in batch:
            nsp = pooled @ params["nsp_w"].astype(jnp.float32) + \
                params["nsp_b"].astype(jnp.float32)
            nsp_logp = jax.nn.log_softmax(nsp, axis=-1)
            nsp_lab = batch["nsp_labels"].astype(jnp.int32)
            total = total - jnp.mean(
                jnp.take_along_axis(nsp_logp, nsp_lab[:, None],
                                    axis=-1))
        return total
    return loss
