"""mxtpu.models — flagship model families, TPU-first functional cores.

The reference shipped its model breadth through
``python/mxnet/gluon/model_zoo/`` (CNNs) and the GluonNLP ecosystem
[path cite — unverified]. The rebuild keeps a Gluon model_zoo for API
parity and, in addition, provides functional cores here: pure
``forward(cfg, params, ...)`` functions over parameter pytrees that
compose directly with ``mxtpu.parallel`` (sharding rules, jitted train
step, remat, scan-over-layers) — the idiomatic shape for pjit/XLA.
"""
from . import bert
from . import llama
from . import resnet
from .bert import BertConfig
from .llama import LlamaConfig
from .resnet import ResNetConfig

__all__ = ["llama", "resnet", "LlamaConfig", "ResNetConfig"]
