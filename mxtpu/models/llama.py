"""Llama-family transformer — the flagship LLM (BASELINE config 5:
"Llama-3-8B ... stress hybridize→HLO at LLM scale").

No reference counterpart exists (MXNet predates Llama; its nearest
artifact is the interleaved-MHA contrib op,
``src/operator/contrib/transformer.cc`` [path cite — unverified]), so
this is a TPU-first design rather than a rebuild:

- **functional core**: pure ``forward(cfg, params, tokens)`` over a
  parameter pytree; composes with ``mxtpu.parallel.step`` for the
  jitted, donated, mesh-sharded train step.
- **scan-over-layers**: per-layer params are stacked on a leading layer
  dim and the block is a ``lax.scan`` — HLO stays O(1) in depth, which
  is what keeps Llama-8B trace/compile time sane (SURVEY.md §7.2.2).
- **remat**: ``jax.checkpoint`` around each layer when
  ``cfg.remat=True`` trades FLOPs for HBM (the reference's
  mirror/memonger had the same role).
- **GQA + RoPE + SwiGLU + RMSNorm**, bf16 activations / f32 params,
  f32 logits for a stable softmax.
- **parallelism-aware**: ``sharding_rules`` gives Megatron-style tp
  sharding + fsdp; activations are sequence-sharded over ``sp`` and the
  attention inner loop can run as ring attention
  (``mxtpu.ops.attention.ring_attention``) under ``shard_map``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import (flash_attention, dense_attention,
                             ring_attention, ulysses_attention,
                             slot_decode_attention,
                             paged_decode_attention)
from ..parallel.sharding import ShardingRules, constrain
from ..parallel.sharding import mcon as _mcon

__all__ = ["LlamaConfig", "init_params", "forward", "forward_hidden",
           "loss_fn", "chunked_softmax_xent", "sharding_rules",
           "CONFIGS", "init_cache", "cache_specs", "prefill",
           "chunked_prefill", "decode_step", "generate",
           "quantize_params_int8", "int8_sharding_rules",
           "sample_logits", "init_slot_cache", "slot_cache_specs",
           "prefill_slot", "decode_slots", "prefill_detached",
           "prefill_detached_chunk", "inject_slot_kv",
           "paged_cache_specs", "init_paged_cache",
           "decode_slots_paged", "prefill_slot_paged",
           "inject_paged_kv", "copy_page", "decode_slots_spec"]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336          # SwiGLU inner dim
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16        # activation/compute dtype
    param_dtype: Any = jnp.float32
    attn_impl: str = "flash"         # flash | dense | ring | ulysses
    remat: bool = True
    # None = full per-layer remat; "dots_no_batch" saves weight-matmul
    # outputs and recomputes only elementwise/attention in the backward
    # (MaxText-style "minimal" policy: ~25% less recompute FLOPs for a
    # modest activation-memory increase)
    remat_policy: Optional[str] = None
    scan_layers: bool = True
    tie_embeddings: bool = False
    # cross-entropy vocab chunk: 0 = auto (chunked when the (B,S,V)
    # logits would dominate HBM, i.e. vocab > 16384), None/False =
    # always materialize full logits, int = explicit chunk width
    ce_chunk: Optional[int] = 0
    # Mixture-of-Experts FFN (expert parallelism over the mesh 'ep'
    # axis): 0 = dense FFN; >0 replaces every layer's FFN with that
    # many SwiGLU experts (parallel.moe)
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


# Named configs; llama3_8b is the BASELINE config-5 target, the small
# ones are for tests/dryrun.
CONFIGS: Dict[str, LlamaConfig] = {
    "tiny": LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                        remat=False),
    "llama3_8b": LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, hidden_dim=14336,
                             max_seq_len=8192),
    "llama2_7b": LlamaConfig(vocab_size=32000, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=32, hidden_dim=11008,
                             rope_theta=10000.0, max_seq_len=4096),
    # Mixtral-8x7B-class MoE (≈46.7B params, 12.9B active/token):
    # 8 SwiGLU experts per layer, top-2 routing — the expert-parallel
    # flagship config (AOT-gated in bench.py aot_moe)
    "mixtral_8x7b": LlamaConfig(vocab_size=32000, dim=4096,
                                n_layers=32, n_heads=32, n_kv_heads=8,
                                hidden_dim=14336, rope_theta=1e6,
                                max_seq_len=4096, moe_experts=8,
                                moe_top_k=2),
}


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------
def _init_layer(key, cfg: LlamaConfig, n: int):
    """Stacked params for n layers (leading dim = layer index)."""
    hd = cfg.head_dim
    ks = jax.random.split(key, 8)
    d = cfg.param_dtype
    # small-init (scaled by fan-in) — GPT-2/Llama style
    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, d) / math.sqrt(fan_in))
    out = {
        "attn_norm": jnp.ones((n, cfg.dim), d),
        "wq": init(ks[0], (n, cfg.dim, cfg.n_heads * hd), cfg.dim),
        "wk": init(ks[1], (n, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
        "wv": init(ks[2], (n, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
        "wo": init(ks[3], (n, cfg.n_heads * hd, cfg.dim),
                   cfg.n_heads * hd * 2 * cfg.n_layers),
        "ffn_norm": jnp.ones((n, cfg.dim), d),
    }
    E = cfg.moe_experts
    if E:
        out["moe_gate"] = init(ks[7], (n, cfg.dim, E), cfg.dim)
        out["w_gate"] = init(ks[4], (n, E, cfg.dim, cfg.hidden_dim),
                             cfg.dim)
        out["w_up"] = init(ks[5], (n, E, cfg.dim, cfg.hidden_dim),
                           cfg.dim)
        out["w_down"] = init(ks[6], (n, E, cfg.hidden_dim, cfg.dim),
                             cfg.hidden_dim * 2 * cfg.n_layers)
    else:
        out["w_gate"] = init(ks[4], (n, cfg.dim, cfg.hidden_dim),
                             cfg.dim)
        out["w_up"] = init(ks[5], (n, cfg.dim, cfg.hidden_dim), cfg.dim)
        out["w_down"] = init(ks[6], (n, cfg.hidden_dim, cfg.dim),
                             cfg.hidden_dim * 2 * cfg.n_layers)
    return out


def init_params(cfg: LlamaConfig, rng: Optional[jax.Array] = None):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    k_emb, k_layers, k_head = jax.random.split(rng, 3)
    params = {
        "tok_embed": jax.random.normal(
            k_emb, (cfg.vocab_size, cfg.dim), cfg.param_dtype) * 0.02,
        "layers": _init_layer(k_layers, cfg, cfg.n_layers),
        "final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jax.random.normal(
            k_head, (cfg.dim, cfg.vocab_size), cfg.param_dtype) \
            / math.sqrt(cfg.dim)
    return params


# ---------------------------------------------------------------------------
# sharding
# ---------------------------------------------------------------------------
def sharding_rules(cfg: Optional[LlamaConfig] = None) -> ShardingRules:
    """Megatron tp + fsdp placement. Layer-stacked params carry a
    leading (unsharded) layer dim. Embedding rows over tp so the
    one-hot matmul psums over tp; lm_head columns over tp (vocab-
    parallel logits). With MoE the expert banks gain a leading E dim
    sharded over ep (expert parallelism) while keeping the same
    fsdp/tp layout per expert.

    TODO(pp): there is deliberately no ``pp`` axis here yet. GPipe
    microbatching exists and is differentiable+tested standalone
    (``parallel/pipeline.py``, test_parallel), but on the ≤8-device
    meshes this repo can measure, fsdp×tp (+sp/ep) dominates a
    pipeline that idles (stages-1)/(stages-1+microbatches) of the
    chips, so the flagship composition is parked until a topology that
    needs it (cross-host meshes where pp's point-to-point beats fsdp's
    all-gather). Owned by the parity-shim row in COMPONENTS.md — keep
    these two in sync when the composition lands."""
    L = None  # leading layer axis of scanned params: never sharded
    moe = bool(cfg and cfg.moe_experts)
    ffn_up = (P(L, "ep", "fsdp", "tp") if moe else P(L, "fsdp", "tp"))
    ffn_dn = (P(L, "ep", "tp", "fsdp") if moe else P(L, "tp", "fsdp"))
    return ShardingRules([
        (r"tok_embed$",        P("tp", "fsdp")),
        (r"layers/w[qkv]$",    P(L, "fsdp", "tp")),   # column parallel
        (r"layers/wo$",        P(L, "tp", "fsdp")),   # row parallel
        (r"layers/moe_gate$",  P()),
        (r"layers/w_(gate|up)$", ffn_up),
        (r"layers/w_down$",    ffn_dn),
        (r"norm",              P()),
        (r"lm_head$",          P("fsdp", "tp")),
        (r".*",                P()),
    ])


# activation specs (sequence sharded over sp)
_ACT = P(("dp", "fsdp"), "sp", None)            # (batch, seq, dim)
_QKV = P(("dp", "fsdp"), "tp", "sp", None)      # (batch, heads, seq, hd)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def rms_norm(x, weight, eps):
    x32 = x.astype(jnp.float32)
    inv = lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight.astype(x.dtype)


def rope_tables(cfg: LlamaConfig, seq_len: int, offset: int = 0):
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta **
                      (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    t = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                 # (seq, hd/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin):
    """x: (b, h, s, hd); rotate-half convention."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attention(cfg: LlamaConfig, q, k, v, mesh: Optional[Mesh]):
    sp_ok = mesh is not None and "sp" in mesh.axis_names
    if cfg.attn_impl in ("ring", "ulysses") and not sp_ok:
        raise ValueError(
            f"attn_impl={cfg.attn_impl!r} needs a mesh with an 'sp' "
            "axis (got mesh="
            f"{None if mesh is None else mesh.axis_names}); pass "
            "mesh= to forward/loss_fn or use 'flash'")
    if cfg.attn_impl in ("ring", "ulysses") and sp_ok:
        kernel = ring_attention if cfg.attn_impl == "ring" \
            else ulysses_attention
        from ..parallel.compat import shard_map
        fn = shard_map(
            partial(kernel, axis_name="sp", causal=True),
            mesh=mesh, in_specs=(_QKV, _QKV, _QKV), out_specs=_QKV,
            check_vma=False)
        return fn(q, k, v)
    if cfg.attn_impl == "dense":
        return dense_attention(q, k, v, causal=True)
    return flash_attention(q, k, v, causal=True)


def _layer(cfg: LlamaConfig, mesh, cos, sin, x, lp):
    """One transformer block. x: (b, s, dim) in cfg.dtype."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"].astype(dt)).reshape(b, s, cfg.n_heads, hd)
    k = (h @ lp["wk"].astype(dt)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (h @ lp["wv"].astype(dt)).reshape(b, s, cfg.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)    # (b, h, s, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    q = constrain(q, *_QKV)
    k = constrain(k, *_QKV)
    v = constrain(v, *_QKV)
    o = _attention(cfg, q, k, v, mesh)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    x = x + constrain(o @ lp["wo"].astype(dt), *_ACT)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    delta, aux = _ffn(cfg, lp, h, mesh)
    x = x + constrain(delta, *_ACT)
    return x, aux


def _ffn(cfg: LlamaConfig, lp, h, mesh, serving: bool = False):
    """FFN residual delta: dense SwiGLU, or the MoE expert bank when
    ``cfg.moe_experts`` is set (expert parallelism over 'ep';
    ``parallel.moe``). Returns (delta, aux) — aux is the MoE
    load-balancing term, 0 for dense. ``serving`` switches MoE to the
    EXACT dropless path (moe_ffn_dense: routing is a pure per-token
    function, linear in T) — the cached prefill/decode path uses it so
    generation never depends on batch composition."""
    dt = h.dtype
    if cfg.moe_experts:
        from ..parallel.moe import moe_ffn, moe_ffn_dense
        b, s, d = h.shape
        mp = {"gate": lp["moe_gate"], "w_gate": lp["w_gate"],
              "w_up": lp["w_up"], "w_down": lp["w_down"]}
        if serving:
            out, aux = moe_ffn_dense(mp, h.reshape(b * s, d),
                                     top_k=cfg.moe_top_k, mesh=mesh)
        else:
            out, aux = moe_ffn(mp, h.reshape(b * s, d),
                               top_k=cfg.moe_top_k,
                               capacity_factor=cfg.moe_capacity,
                               mesh=mesh)
        return out.reshape(b, s, d), aux
    gate = jax.nn.silu(h @ _wq8(lp["w_gate"], dt))
    up = h @ _wq8(lp["w_up"], dt)
    return (gate * up) @ _wq8(lp["w_down"], dt), \
        jnp.zeros((), jnp.float32)


def forward_hidden(cfg: LlamaConfig, params, tokens,
                   mesh: Optional[Mesh] = None, with_aux: bool = False):
    """tokens: (batch, seq) int32 → final-norm hidden states
    (batch, seq, dim) in cfg.dtype — everything but the lm_head
    matmul, so losses can stream the vocab dim instead of
    materializing (B, S, V) logits. With ``with_aux`` also returns the
    per-layer-mean MoE load-balancing aux (0 for dense configs)."""
    b, s = tokens.shape
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    x = constrain(x, *_ACT)
    cos, sin = rope_tables(cfg, s)

    layer = partial(_layer, cfg, mesh, cos, sin)
    if cfg.remat:
        if cfg.remat_policy == "dots_no_batch":
            layer = jax.checkpoint(
                layer, policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)
        elif cfg.remat_policy is None:
            layer = jax.checkpoint(layer)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                "(use None or 'dots_no_batch')")

    if cfg.scan_layers:
        def body(x, lp):
            return layer(x, lp)
        x, auxes = lax.scan(body, x, params["layers"])
        aux = jnp.mean(auxes)
    else:
        aux = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[i], params["layers"])
            x, a = layer(x, lp)
            aux = aux + a / cfg.n_layers

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x, aux) if with_aux else x


def _head(cfg: LlamaConfig, params):
    return (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])


def _wq8(w, dt):
    """Serving weight loader: a raw array, or a weight-only int8 dict
    ``{'q8': int8, 's8': f32 per-out-channel}`` (see
    :func:`quantize_params_int8`). The dequant multiply is in-program;
    XLA fuses it into the consuming matmul's operand read, so int8
    halves the HBM weight traffic that dominates small-batch decode."""
    if isinstance(w, dict):
        return w["q8"].astype(dt) * w["s8"].astype(dt)
    return w.astype(dt)


def quantize_params_int8(cfg: LlamaConfig, params):
    """Weight-only int8 quantization for SERVING (prefill/decode/
    generate — the cached path; the training forward does not consume
    quantized trees). Symmetric per-output-channel scales over the
    contracted axis: ``w ≈ q8 · s8`` with q8 ∈ [-127, 127] int8 and
    s8 = max|w| / 127 per output column. Activations, norms, and the
    KV cache stay in ``cfg.dtype`` — this is the regime analysis of
    docs/perf.md ("int8 serving becomes interesting only where
    weights dominate the step time — multi-GB models at small
    batch"): llama3_8b tp8 decode. Shard with
    :func:`int8_sharding_rules`."""
    if cfg.moe_experts:
        raise NotImplementedError(
            "int8 serving quantization covers dense configs; the MoE "
            "expert banks serve via the dense-mixture path in bf16")

    def q(w):
        s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2,
                    keepdims=True) / 127.0
        s = jnp.maximum(s, 1e-12)
        q8 = jnp.clip(jnp.round(w.astype(jnp.float32) / s),
                      -127, 127).astype(jnp.int8)
        return {"q8": q8, "s8": s.astype(jnp.float32)}

    out = {"tok_embed": q(params["tok_embed"]),
           "final_norm": params["final_norm"],
           "layers": dict(params["layers"])}
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        out["layers"][name] = q(params["layers"][name])
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"])
    return out


def int8_sharding_rules(cfg: Optional[LlamaConfig] = None) \
        -> ShardingRules:
    """Placement for :func:`quantize_params_int8` trees: q8 leaves
    inherit their weight's Megatron spec; s8 scales (size-1 on every
    axis but the output channels) shard only the output axis."""
    L = None
    return ShardingRules([
        (r"tok_embed/q8$",        P("tp", "fsdp")),
        (r"tok_embed/s8$",        P(None, "fsdp")),
        (r"layers/w[qkv]/q8$",    P(L, "fsdp", "tp")),
        (r"layers/w[qkv]/s8$",    P(L, None, "tp")),
        (r"layers/wo/q8$",        P(L, "tp", "fsdp")),
        (r"layers/wo/s8$",        P(L, None, "fsdp")),
        (r"layers/w_(gate|up)/q8$", P(L, "fsdp", "tp")),
        (r"layers/w_(gate|up)/s8$", P(L, None, "tp")),
        (r"layers/w_down/q8$",    P(L, "tp", "fsdp")),
        (r"layers/w_down/s8$",    P(L, None, "fsdp")),
        (r"lm_head/q8$",          P("fsdp", "tp")),
        (r"lm_head/s8$",          P(None, "tp")),
        (r"norm",                 P()),
        (r".*",                   P()),
    ])


def forward(cfg: LlamaConfig, params, tokens,
            mesh: Optional[Mesh] = None):
    """tokens: (batch, seq) int32 → logits (batch, seq, vocab) f32."""
    x = forward_hidden(cfg, params, tokens, mesh=mesh)
    logits = jnp.einsum("bsd,dv->bsv", x,
                        _head(cfg, params).astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return constrain(logits, ("dp", "fsdp"), "sp", None)


# ---------------------------------------------------------------------------
# loss
# ---------------------------------------------------------------------------
def chunked_softmax_xent(x, head, targets, chunk: int):
    """Per-token causal-LM NLL via a streaming logsumexp over vocab
    chunks — the full (B, S, V) logits tensor is NEVER materialized
    (VERDICT r2 #5: at seq 2048 × vocab 32k the f32 logits alone are
    ~1 GB and dominate the llama step's HBM traffic).

    x: (b, s, d) compute dtype; head: (d, V); targets: (b, s) int.
    Each scan step matmuls one (d, chunk) slice (MXU-friendly N =
    chunk), folds it into running (max, sumexp, target-logit) carries
    of shape (b, s), and is wrapped in ``jax.checkpoint`` so the
    backward recomputes chunk logits instead of saving them.
    """
    b, s, d = x.shape
    V = head.shape[1]
    n_chunks = -(-V // chunk)
    Vp = n_chunks * chunk
    if Vp != V:           # zero-pad; padded cols masked to -inf below
        head = jnp.pad(head, ((0, 0), (0, Vp - V)))

    def body(carry, i):
        m, acc, tl = carry
        W = lax.dynamic_slice_in_dim(head, i * chunk, chunk, axis=1)
        logits = jnp.einsum("bsd,dv->bsv", x, W,
                            preferred_element_type=jnp.float32)
        col0 = i * chunk
        if Vp != V:
            cols = col0 + jnp.arange(chunk)
            logits = jnp.where(cols < V, logits, -jnp.inf)
        cm = logits.max(-1)
        nm = jnp.maximum(m, cm)
        acc = acc * jnp.exp(m - nm) + \
            jnp.exp(logits - nm[..., None]).sum(-1)
        local = targets - col0
        hit = (local >= 0) & (local < chunk)
        got = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[..., None],
            axis=-1)[..., 0]
        tl = tl + jnp.where(hit, got, 0.0)
        return (nm, acc, tl), None

    init = (jnp.full((b, s), -jnp.inf, jnp.float32),
            jnp.zeros((b, s), jnp.float32),
            jnp.zeros((b, s), jnp.float32))
    (m, acc, tl), _ = lax.scan(jax.checkpoint(body), init,
                               jnp.arange(n_chunks))
    return m + jnp.log(acc) - tl


def _resolve_ce_chunk(cfg: LlamaConfig) -> int:
    """0 = no chunking. Auto mode picks ~8k-wide chunks (a good MXU N)
    once the vocab is big enough for logits to dominate HBM."""
    if cfg.ce_chunk is None or cfg.ce_chunk is False:
        return 0                       # explicit opt-out
    if cfg.ce_chunk == 0:              # auto
        return 8192 if cfg.vocab_size > 16384 else 0
    return int(cfg.ce_chunk)


def loss_fn(cfg: LlamaConfig, mesh: Optional[Mesh] = None):
    """Causal-LM loss for ``parallel.step.make_train_step``: batch is a
    dict with 'tokens' (b, s) and optional 'mask' (b, s) — predicts
    token t+1 from prefix ≤ t. Large vocabs take the chunked-CE path
    (see ``chunked_softmax_xent``)."""
    def loss(params, batch):
        tokens = batch["tokens"]
        x, moe_aux = forward_hidden(cfg, params, tokens, mesh=mesh,
                                    with_aux=True)
        x = x[:, :-1]
        targets = tokens[:, 1:]
        mask = batch.get("mask")
        mask = (jnp.ones_like(targets, jnp.float32) if mask is None
                else mask[:, 1:].astype(jnp.float32))
        head = _head(cfg, params).astype(cfg.dtype)
        chunk = _resolve_ce_chunk(cfg)
        if chunk:
            nll = chunked_softmax_xent(x, head, targets, chunk)
        else:
            logits = jnp.einsum("bsd,dv->bsv", x, head,
                                preferred_element_type=jnp.float32)
            logits = constrain(logits, ("dp", "fsdp"), "sp", None)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        ce = (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        if cfg.moe_experts:
            ce = ce + cfg.moe_aux_weight * moe_aux
        return ce
    return loss


# ---------------------------------------------------------------------------
# inference: KV-cache prefill + decode (VERDICT r2 #4)
# ---------------------------------------------------------------------------
# The reference shipped a dedicated fixed-graph inference surface
# (``src/c_api/c_predict_api.cc`` + ``benchmark_score.py`` [path cites
# — unverified]); the TPU-era equivalent for a causal LM is
# prefill-then-decode over a preallocated KV cache: static shapes
# throughout (cache sized to max_len, position as a traced scalar), so
# the whole generate loop compiles to ONE program with a lax.scan —
# no per-token dispatch, no dynamic shapes.
#
# Sharded serving (VERDICT r3 #1): at 8B scale a single chip cannot
# hold the weights (16GB bf16 vs 16GB v5e HBM, before the cache), so
# decode is mesh-first: pass ``mesh=`` and the cache shards over the
# kv-head axis (tp) and the batch axis (dp/fsdp) while the params keep
# their rule-table placement — the same Megatron layout the train step
# uses, so a trained sharded state serves without resharding.

def cache_specs(cfg: LlamaConfig, mesh: Mesh, batch_size: int):
    """PartitionSpecs for the KV cache on ``mesh``: batch over the
    data axes, kv heads over tp. An axis is dropped when the mesh
    lacks it or the dim isn't divisible (tiny test configs / odd
    batches) — a dropped axis means replication, never an error."""
    batch_axes = tuple(a for a in ("dp", "fsdp")
                       if a in mesh.axis_names and mesh.shape[a] > 1)
    nb = 1
    for a in batch_axes:
        nb *= mesh.shape[a]
    if batch_axes and batch_size % nb:
        batch_axes = ()
    tp = ("tp" if "tp" in mesh.axis_names
          and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None)
    kv = P(None, batch_axes if batch_axes else None, tp, None, None)
    return {"k": kv, "v": kv, "pos": P()}


def init_cache(cfg: LlamaConfig, batch_size: int, max_len: int,
               mesh: Optional[Mesh] = None):
    """Preallocated GQA KV cache: (L, b, n_kv_heads, max_len, hd) in
    the compute dtype, plus the traced write position. With ``mesh``
    the cache materializes directly sharded per :func:`cache_specs` —
    it never stages through one device (an 8B 8k-context cache is
    larger than a v5e chip's HBM)."""
    hd = cfg.head_dim
    shape = (cfg.n_layers, batch_size, cfg.n_kv_heads, max_len, hd)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "pos": jnp.zeros((), jnp.int32)}

    if mesh is None:
        return build()
    from jax.sharding import NamedSharding
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        cache_specs(cfg, mesh, batch_size),
        is_leaf=lambda s: isinstance(s, P))
    return jax.jit(build, out_shardings=shardings)()


# (the decode path's explicit-mesh constraints use sharding.mcon,
# imported as _mcon above)


def _layer_cached(cfg: LlamaConfig, cos, sin, pos, max_len,
                  mesh, kvspec, x, lp, ck, cv):
    """One block over the cache. x: (b, s, dim) where s is the prompt
    length (prefill) or 1 (decode). ck/cv: (b, kvh, max_len, hd).
    Returns (x, ck, cv) with the new keys/values written at
    [pos : pos+s]. ``kvspec`` is the per-layer cache PartitionSpec
    (cache_specs minus the scanned layer dim); with a mesh the cache
    write is pinned to it so XLA never re-lays the cache mid-scan."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _wq8(lp["wq"], dt)).reshape(b, s, cfg.n_heads, hd)
    k = (h @ _wq8(lp["wk"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (h @ _wq8(lp["wv"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)          # (b, h, s, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    # pin the batch + head axes — the reshape/transpose chain above can
    # lose the propagated sharding, and a lost head sharding makes the
    # attention materialize the full cache per device. BOTH axes come
    # from the cache spec (kvspec[0]/[1]) so the pins honor the same
    # divisibility guards cache_specs applies: an odd batch or a tp
    # that doesn't divide the kv heads replicates that axis everywhere
    # instead of fighting the cache with a per-layer reshard.
    batch_ax = kvspec[0] if kvspec is not None else ("dp", "fsdp")
    head_ax = kvspec[1] if kvspec is not None else None
    q = _mcon(mesh, q, batch_ax, head_ax, None, None)
    k = _mcon(mesh, k, batch_ax, head_ax, None, None)
    v = _mcon(mesh, v, batch_ax, head_ax, None, None)
    zero = jnp.zeros((), jnp.int32)
    idx = (zero, zero, pos.astype(jnp.int32), zero)
    ck = lax.dynamic_update_slice(ck, k.astype(dt), idx)
    cv = lax.dynamic_update_slice(cv, v.astype(dt), idx)
    if mesh is not None:
        from jax.sharding import NamedSharding
        ck = lax.with_sharding_constraint(
            ck, NamedSharding(mesh, kvspec))
        cv = lax.with_sharding_constraint(
            cv, NamedSharding(mesh, kvspec))

    # attend q against the whole cache, masked to the causal prefix:
    # key j visible to query i iff j <= pos + i. GQA-native: group the
    # q heads per kv head instead of materializing repeated KV (the
    # repeat would copy the whole cache every layer, every step)
    rep = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, cfg.n_kv_heads, rep, s, hd)
    logits = jnp.einsum("bgrsd,bgkd->bgrsk", qg, ck,
                        preferred_element_type=jnp.float32)
    logits = logits / math.sqrt(hd)
    kpos = jnp.arange(max_len)[None, :]             # (1, max_len)
    qpos = pos + jnp.arange(s)[:, None]             # (s, 1)
    logits = jnp.where(kpos <= qpos, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(dt)
    o = jnp.einsum("bgrsk,bgkd->bgrsd", p, cv)
    o = o.reshape(b, cfg.n_heads, s, hd)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    x = x + _mcon(mesh, o @ _wq8(lp["wo"], dt),
                  batch_ax, None, None)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    # serving: exact dropless routing — generation must not depend on
    # how many tokens share this step (decode sees T=batch, prefill
    # T=batch·s), and capacity tensors must stay linear in T
    delta, _ = _ffn(cfg, lp, h, mesh, serving=True)
    x = x + _mcon(mesh, delta, batch_ax, None, None)
    return x, ck, cv


def _forward_cached(cfg: LlamaConfig, params, tokens, cache,
                    last_only: bool = False,
                    mesh: Optional[Mesh] = None,
                    last_index=None):
    """Shared prefill/decode body: runs the stack over the cache and
    returns (logits (b, s, V) f32, new cache). ``last_only`` applies
    the lm_head to the final position only — generation never needs
    (and must not pay for) full-prompt logits. ``last_index`` (a traced
    scalar) instead applies it to that single position — the bucketed
    serving prefill pads prompts to a bucket, so "last" is the last
    REAL position, not the last row. ``mesh`` pins the cache
    and residual-stream shardings (see ``cache_specs``); params attend
    against the cache in their training placement, so the tp einsums
    stay local and XLA reduces over tp exactly where the Megatron
    layout implies."""
    b, s = tokens.shape
    max_len = cache["k"].shape[3]
    pos = cache["pos"]
    kvspec = (cache_specs(cfg, mesh, b)["k"] if mesh is not None
              else None)
    if kvspec is not None:               # per-layer view: drop the
        kvspec = P(*kvspec[1:])          # scanned leading L axis
    batch_ax = kvspec[0] if kvspec is not None else ("dp", "fsdp")
    emb = params["tok_embed"]
    if isinstance(emb, dict):        # weight-only int8: dequant the
        # GATHERED rows only (scale is per-dim-channel)
        x = emb["q8"][tokens].astype(cfg.dtype) * \
            emb["s8"][0].astype(cfg.dtype)
    else:
        x = emb[tokens].astype(cfg.dtype)
    x = _mcon(mesh, x, batch_ax, None, None)
    # rope tables for absolute positions pos..pos+s from one static
    # (max_len, hd/2) table — keeps the program shape-static
    cos_t, sin_t = rope_tables(cfg, max_len)
    cos = lax.dynamic_slice_in_dim(cos_t, pos, s, axis=0)
    sin = lax.dynamic_slice_in_dim(sin_t, pos, s, axis=0)

    def body(x, xs):
        lp, ck, cv = xs
        x, ck, cv = _layer_cached(cfg, cos, sin, pos, max_len,
                                  mesh, kvspec, x, lp, ck, cv)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(body, x,
                           (params["layers"], cache["k"], cache["v"]))
    if mesh is not None:
        # the scan re-stacks the per-layer cache; pin the stacked
        # result or the whole cache round-trips through a replicated
        # temp (full-cache bytes per device)
        from jax.sharding import NamedSharding
        full = NamedSharding(mesh, cache_specs(cfg, mesh, b)["k"])
        ck = lax.with_sharding_constraint(ck, full)
        cv = lax.with_sharding_constraint(cv, full)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_index is not None:
        x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    elif last_only:
        x = x[:, -1:]
    hw = (_wq8(params["tok_embed"], cfg.dtype).T if cfg.tie_embeddings
          else _wq8(params["lm_head"], cfg.dtype))
    logits = jnp.einsum("bsd,dv->bsv", x, hw,
                        preferred_element_type=jnp.float32)
    logits = _mcon(mesh, logits, batch_ax, None, None)
    new_cache = {"k": ck, "v": cv, "pos": pos + s}
    return logits, new_cache


def prefill(cfg: LlamaConfig, params, tokens, cache,
            mesh: Optional[Mesh] = None, last_only: bool = False):
    """Run the prompt through the stack, filling the cache. Returns
    (logits (b, s, V) f32 for every prompt position, cache). Serving
    only consumes the final position — pass ``last_only=True`` and s=1
    comes back; at 8B the full-prompt logits are the prefill peak
    (8×2048×128256 f32 ≈ 8.4GB, vs ~0.004GB for the last position)."""
    return _forward_cached(cfg, params, tokens, cache, mesh=mesh,
                           last_only=last_only)


def chunked_prefill(cfg: LlamaConfig, params, tokens, cache,
                    chunk_size: int, mesh: Optional[Mesh] = None):
    """Streaming prefill (VERDICT r4 #5 — the long-context serving
    half): run the prompt through the cached stack in ``chunk_size``
    slices via one ``lax.scan``, so peak activation memory scales
    with the CHUNK, not the prompt. Single-shot prefill materializes
    per-layer attention logits of (b, h, s, ctx) f32 — at llama3_8b
    with a 32k prompt that is ~1 TB and cannot compile; chunked at
    1k it is ~34 GB/layer-step sharded over tp. Only the final
    position's logits are computed per chunk (s=1 head matmul), and
    only the last chunk's survive.

    Prompt lengths that don't divide ``chunk_size`` are handled by a
    trailing remainder pass (a second compiled shape) — NEVER pad the
    prompt: the cached path has no pad masking, so pad tokens would
    occupy real cache slots and shift every RoPE position.

    Returns (logits (b, 1, V) f32 for the last prompt position,
    cache) — exactly ``prefill(..., last_only=True)``
    (``test_llama_chunked_prefill_matches_single_shot``)."""
    b, s = tokens.shape
    n, rem = divmod(s, chunk_size)
    logits = None
    if n == 1 and rem == 0:
        return _forward_cached(cfg, params, tokens, cache,
                               last_only=True, mesh=mesh)
    if n:
        # (b, n·c) → (n, b, c): scan consumes the leading axis. The
        # per-chunk logits ride in the CARRY (same (b, 1, V) shape
        # every step), not the stacked scan output — stacking n
        # last-position logits would buffer n·b·V f32 (~123 MB at
        # 32k/llama3_8b) only to keep one slice
        chunks = tokens[:, :n * chunk_size] \
            .reshape(b, n, chunk_size).transpose(1, 0, 2)

        def body(carry, chunk):
            cache, _ = carry
            lg, cache = _forward_cached(cfg, params, chunk, cache,
                                        last_only=True, mesh=mesh)
            return (cache, lg), None

        zeros = jnp.zeros((b, 1, cfg.vocab_size), jnp.float32)
        (cache, logits), _ = lax.scan(body, (cache, zeros), chunks)
    if rem:
        logits, cache = _forward_cached(cfg, params,
                                        tokens[:, n * chunk_size:],
                                        cache, last_only=True,
                                        mesh=mesh)
    return logits, cache


def decode_step(cfg: LlamaConfig, params, token, cache,
                mesh: Optional[Mesh] = None):
    """One autoregressive step. token: (b, 1) int32. Returns
    (logits (b, V) f32 for the next position, cache)."""
    logits, cache = _forward_cached(cfg, params, token, cache,
                                    mesh=mesh)
    return logits[:, 0], cache


def sample_logits(rng, lg, temperature=0.0, top_k=None, top_p=None):
    """THE sampler — one shared helper for :func:`generate` and the
    continuous-batching serving engine (``mxtpu.serve``). lg: (b, V)
    f32 logits → (b,) int32 tokens.

    Two calling modes, numerically aligned token-for-token:

    - **static** (all of temperature/top_k/top_p are Python numbers or
      None): specializes the jitted graph per config — greedy compiles
      to a bare argmax, top-k uses ``lax.top_k`` — the fast path
      ``generate``'s one-program decode loop wants.
    - **traced** (any of them a jax/numpy array): one graph serves
      every per-row mix — temperature (b,), top_k (b,) ints (vocab
      size disables), top_p (b,) (1.0 disables), with temperature 0
      rows selecting argmax. This is how the serving engine runs
      requests with different sampling configs through ONE compiled
      decode program, with tokens bit-matching the static path: the
      top-k threshold is the same kth VALUE, the nucleus keep-mask the
      same formula, so the masked logits agree and
      ``jax.random.categorical`` sees identical inputs.

    Nucleus semantics (both modes): keep the smallest prefix of the
    sorted distribution whose mass reaches p — probabilities computed
    ONCE, and the survivor set applied as a value threshold (the kept
    minimum) rather than a full-vocab scatter."""
    static = (isinstance(temperature, (int, float))
              and (top_k is None or isinstance(top_k, int))
              and (top_p is None or isinstance(top_p, (int, float))))
    V = lg.shape[-1]
    if static:
        if temperature == 0.0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        lg = lg / temperature
        if top_k is not None and top_k < V:
            kth = lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        if top_p is not None and top_p < 1.0:
            lg = _nucleus_mask(lg, top_p)
        return jax.random.categorical(rng, lg, axis=-1) \
            .astype(jnp.int32)

    def col(x, dtype):          # broadcast a scalar or (b,) over vocab
        x = jnp.asarray(x, dtype)
        return x.reshape(x.shape + (1,) * (lg.ndim - x.ndim))

    t_col = col(temperature, jnp.float32)
    k_col = jnp.clip(col(V if top_k is None else top_k, jnp.int32),
                     1, V)
    p_col = col(1.0 if top_p is None else top_p, jnp.float32)

    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    slg = lg / jnp.where(t_col == 0.0, 1.0, t_col)
    # top-k as a value threshold: the kth-largest VALUE equals
    # lax.top_k's kth element, so the mask matches the static path
    srt = jnp.take_along_axis(slg, jnp.argsort(-slg, axis=-1), axis=-1)
    kth = jnp.take_along_axis(srt, jnp.broadcast_to(
        k_col - 1, slg.shape[:-1] + (1,)), axis=-1)
    slg = jnp.where(slg < kth, -jnp.inf, slg)
    slg = _nucleus_mask(slg, p_col)
    sampled = jax.random.categorical(rng, slg, axis=-1) \
        .astype(jnp.int32)
    return jnp.where(jnp.squeeze(t_col, -1) == 0.0, greedy, sampled)


def _nucleus_mask(lg, top_p):
    """Mask lg to the top-p nucleus: softmax ONCE over the sorted row,
    keep the smallest prefix reaching p (the top token always
    survives), and apply the survivor set as a >= threshold on the
    kept minimum — no full-vocab scatter."""
    order = jnp.argsort(-lg, axis=-1)
    sorted_lg = jnp.take_along_axis(lg, order, axis=-1)
    probs = jax.nn.softmax(sorted_lg, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (csum - probs) < top_p
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_lg, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(lg >= cutoff, lg, -jnp.inf)


def generate(cfg: LlamaConfig, params, prompt, max_new_tokens: int,
             *, temperature: float = 0.0,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             mesh: Optional[Mesh] = None):
    """Autoregressive generation: prefill + a lax.scan of decode
    steps — ONE jitted program end to end when wrapped in jax.jit
    (max_new_tokens static). temperature=0 is greedy; otherwise
    softmax sampling at the given temperature, optionally truncated to
    the ``top_k`` highest-probability tokens and/or the ``top_p``
    nucleus (smallest prefix of the sorted distribution reaching p —
    both static-shaped: masks, not dynamic vocab slices). With
    ``mesh`` the whole loop runs sharded (cache per
    :func:`cache_specs`, params as placed) — serving the 8B flagship
    needs this: its weights alone exceed one v5e chip's HBM.

    Returns (b, prompt_len + max_new_tokens) tokens."""
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    b, s0 = prompt.shape
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    # init_cache(mesh=) materializes the cache directly sharded: under
    # an outer jit the nested jit's out_shardings become constraints,
    # and called EAGERLY (GluonLlama.generate) the full cache never
    # stages through one device — at 8B that transient replicated
    # cache would be 8.6GB on the default chip
    cache = init_cache(cfg, b, s0 + max_new_tokens, mesh=mesh)
    logits, cache = _forward_cached(cfg, params, prompt, cache,
                                    last_only=True, mesh=mesh)

    def sample(rng, lg):
        return sample_logits(rng, lg, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    rng, sub = jax.random.split(rng)
    first = sample(sub, logits[:, -1])

    def step(carry, _):
        cache, tok, rng = carry
        logits, cache = decode_step(cfg, params, tok[:, None], cache,
                                    mesh=mesh)
        rng, sub = jax.random.split(rng)
        nxt = sample(sub, logits)
        return (cache, nxt, rng), nxt

    (cache, _, _), rest = lax.scan(
        step, (cache, first, rng), None, length=max_new_tokens - 1)
    out = jnp.concatenate(
        [prompt, first[:, None], rest.transpose(1, 0)], axis=1)
    return out


# ---------------------------------------------------------------------------
# continuous-batching serving: slot KV cache + one-program decode
# (the model half of ``mxtpu.serve`` — scheduler/queue live there)
# ---------------------------------------------------------------------------
# ``generate`` above is a WHOLE-BATCH program: every request starts
# together and holds its cache until the slowest one finishes. The
# slot path instead serves a fixed bank of ``max_slots`` independent
# rows: admission overwrites a finished slot in place (Orca-style
# iteration-level scheduling), per-slot length/position vectors drive
# ONE compiled decode program for the full bank, and the length-masked
# ``slot_decode_attention`` kernel confines each slot to its own
# prefix. Prompts prefill through per-bucket programs (padded to a
# power of two), so total compilations stay bounded by the bucket
# count + 1.

def slot_cache_specs(cfg: LlamaConfig, mesh: Mesh):
    """PartitionSpecs for the serving slot state on ``mesh``: kv heads
    over tp (dropped when tp doesn't divide them — replication, never
    an error); the slot axis stays unsharded — admission rewrites one
    row at a time and must not reshard the bank. Per-slot vectors are
    replicated."""
    tp = ("tp" if "tp" in mesh.axis_names
          and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None)
    # trailing Nones trimmed: program outputs come back normalized, and
    # a committed P(..., 'tp', None, None) vs an output P(..., 'tp')
    # would be unequal jit cache keys — one spurious recompile per
    # program on the mesh path
    kv = P(None, None, tp) if tp is not None else P()
    return {"k": kv, "v": kv, "lengths": P(), "tokens": P(),
            "rngs": P()}


def init_slot_cache(cfg: LlamaConfig, max_slots: int, max_len: int,
                    mesh: Optional[Mesh] = None):
    """The serving engine's device state: a fixed slot KV cache
    ``k``/``v`` of (L, max_slots, n_kv_heads, max_len, hd) in the
    compute dtype, plus per-slot ``lengths`` (valid cache entries),
    ``tokens`` (next input token) and ``rngs`` (per-request sampling
    chains). With ``mesh`` the bank materializes directly sharded per
    :func:`slot_cache_specs`."""
    hd = cfg.head_dim
    shape = (cfg.n_layers, max_slots, cfg.n_kv_heads, max_len, hd)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "lengths": jnp.zeros((max_slots,), jnp.int32),
                "tokens": jnp.zeros((max_slots,), jnp.int32),
                "rngs": jnp.zeros((max_slots, 2), jnp.uint32)}

    if mesh is None:
        return build()
    from jax.sharding import NamedSharding
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        slot_cache_specs(cfg, mesh),
        is_leaf=lambda s: isinstance(s, P))
    return jax.jit(build, out_shardings=shardings)()


def _layer_slots(cfg: LlamaConfig, cos, sin, pos, mesh, kvspec,
                 x, lp, ck, cv):
    """One block of the slot decode: x (S, 1, dim) — one new token per
    slot; ck/cv (S, kvh, max_len, hd). Writes each slot's new K/V at
    its OWN position ``pos[i]`` and attends it against its own prefix
    via the length-masked blockwise kernel."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _wq8(lp["wq"], dt)).reshape(b, s, cfg.n_heads, hd)
    k = (h @ _wq8(lp["wk"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (h @ _wq8(lp["wv"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)          # (S, h, 1, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    head_ax = (kvspec[1] if kvspec is not None and len(kvspec) > 1
               else None)
    q = _mcon(mesh, q, None, head_ax, None, None)
    k = _mcon(mesh, k, None, head_ax, None, None)
    v = _mcon(mesh, v, None, head_ax, None, None)

    zero = jnp.zeros((), jnp.int32)

    def write(c, u, p):          # per-slot scatter at its own position
        return lax.dynamic_update_slice(c, u, (zero, p, zero))

    ck = jax.vmap(write)(ck, k.astype(dt), pos)
    cv = jax.vmap(write)(cv, v.astype(dt), pos)
    if mesh is not None:
        from jax.sharding import NamedSharding
        ck = lax.with_sharding_constraint(
            ck, NamedSharding(mesh, kvspec))
        cv = lax.with_sharding_constraint(
            cv, NamedSharding(mesh, kvspec))

    o = slot_decode_attention(q, ck, cv, pos + 1)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    x = x + _mcon(mesh, o @ _wq8(lp["wo"], dt), None, None, None)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    delta, _ = _ffn(cfg, lp, h, mesh, serving=True)
    x = x + _mcon(mesh, delta, None, None, None)
    return x, ck, cv


def decode_slots(cfg: LlamaConfig, params, kv, sv, active,
                 temperature, top_k, top_p,
                 mesh: Optional[Mesh] = None):
    """ONE continuous-batching decode step over the whole slot bank —
    the single compiled program the serving engine keeps hot: per-slot
    position/length arrays drive the RoPE gather, the cache write and
    the length-masked attention, so requests entering and leaving the
    bank never change the program shape (no retraces, ever).

    kv: {"k", "v"} — the big cache bank, safe to DONATE (the engine
    does). sv: {"lengths", "tokens", "rngs"} — the small per-slot
    vectors, deliberately NOT donated so the engine can overlap the
    host read of one step's tokens with the next step's dispatch.
    active: (S,) bool — inactive slots still flow through (fixed
    shape) but their lengths do not advance and their samples are
    discarded by the engine. temperature/top_k/top_p: (S,) per-slot
    sampling config (traced — a mixed batch shares the program).
    Sampling advances each slot's own rng chain exactly as a batch-1
    :func:`generate` would, which is what makes serving output
    bit-identical to per-request generation. Returns
    (sampled (S,) int32, new kv, new sv)."""
    max_len = kv["k"].shape[3]
    lengths = sv["lengths"].astype(jnp.int32)
    pos = jnp.minimum(lengths, max_len - 1)   # per-slot write position
    tokens = sv["tokens"][:, None]
    emb = params["tok_embed"]
    if isinstance(emb, dict):
        x = emb["q8"][tokens].astype(cfg.dtype) * \
            emb["s8"][0].astype(cfg.dtype)
    else:
        x = emb[tokens].astype(cfg.dtype)

    kvspec = None
    if mesh is not None:
        kvspec = P(*tuple(slot_cache_specs(cfg, mesh)["k"])[1:])
    cos_t, sin_t = rope_tables(cfg, max_len)
    cos = cos_t[pos][:, None, None, :]        # (S, 1, 1, hd/2)
    sin = sin_t[pos][:, None, None, :]

    def body(x, xs):
        lp, ck, cv = xs
        x, ck, cv = _layer_slots(cfg, cos, sin, pos, mesh, kvspec,
                                 x, lp, ck, cv)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(body, x,
                           (params["layers"], kv["k"], kv["v"]))
    if mesh is not None:
        from jax.sharding import NamedSharding
        full = NamedSharding(mesh, slot_cache_specs(cfg, mesh)["k"])
        ck = lax.with_sharding_constraint(ck, full)
        cv = lax.with_sharding_constraint(cv, full)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    hw = (_wq8(params["tok_embed"], cfg.dtype).T if cfg.tie_embeddings
          else _wq8(params["lm_head"], cfg.dtype))
    logits = jnp.einsum("bsd,dv->bsv", x, hw,
                        preferred_element_type=jnp.float32)[:, 0]

    def one(key, lg, t, kk, pp):
        # mirror generate's step: split the chain, sample on (1, V)
        key, sub = jax.random.split(key)
        tok = sample_logits(sub, lg[None], temperature=t,
                            top_k=kk, top_p=pp)[0]
        return key, tok

    new_rngs, sampled = jax.vmap(one)(
        sv["rngs"], logits, temperature, top_k, top_p)
    new_lengths = lengths + active.astype(jnp.int32)
    if mesh is not None:
        # pin the small vectors replicated — an unconstrained output
        # sharding would differ from the bank's committed layout and
        # force a second decode compilation on the next step
        sampled = _mcon(mesh, sampled, None)
        new_lengths = _mcon(mesh, new_lengths, None)
        new_rngs = _mcon(mesh, new_rngs, None, None)
    return sampled, {"k": ck, "v": cv}, \
        {"lengths": new_lengths, "tokens": sampled, "rngs": new_rngs}


def prefill_slot(cfg: LlamaConfig, params, tokens, true_len, slot,
                 kv, sv, rng, temperature, top_k, top_p,
                 mesh: Optional[Mesh] = None):
    """Admission: run ONE request's prompt — END-padded to its bucket —
    through the cached stack, write its K/V into row ``slot`` of the
    slot bank, seed the slot's rng/next-token, and sample the first
    generated token. One compiled program per prompt BUCKET (power of
    two), so compilations are bounded by the bucket count no matter
    what lengths arrive.

    End padding is exact: causal masking means no real position ever
    attends a pad (pads sit after the prompt), pad K/V beyond
    ``true_len`` are excluded by the slot's length mask, and each is
    overwritten by a real decode write before the length ever reaches
    it. tokens: (1, bucket); true_len/slot: traced scalars; kv/sv as
    in :func:`decode_slots` (kv donatable). Returns
    (first token (1,), new kv, new sv)."""
    b, bucket = tokens.shape
    hd = cfg.head_dim
    tmp = {"k": jnp.zeros((cfg.n_layers, b, cfg.n_kv_heads, bucket,
                           hd), cfg.dtype),
           "v": jnp.zeros((cfg.n_layers, b, cfg.n_kv_heads, bucket,
                           hd), cfg.dtype),
           "pos": jnp.zeros((), jnp.int32)}
    true_len = jnp.asarray(true_len, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    logits, tmp = _forward_cached(cfg, params, tokens, tmp, mesh=mesh,
                                  last_index=true_len - 1)
    rng, sub = jax.random.split(rng)
    tok = sample_logits(sub, logits[:, 0], temperature=temperature,
                        top_k=top_k, top_p=top_p)
    z = jnp.zeros((), jnp.int32)
    new_kv = {
        "k": lax.dynamic_update_slice(kv["k"], tmp["k"],
                                      (z, slot, z, z, z)),
        "v": lax.dynamic_update_slice(kv["v"], tmp["v"],
                                      (z, slot, z, z, z)),
    }
    new_sv = {
        "lengths": lax.dynamic_update_slice(
            sv["lengths"].astype(jnp.int32), true_len[None],
            (slot,)),
        "tokens": lax.dynamic_update_slice(
            sv["tokens"], tok.astype(sv["tokens"].dtype),
            (slot,)),
        "rngs": lax.dynamic_update_slice(
            sv["rngs"], rng[None].astype(sv["rngs"].dtype),
            (slot, z)),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding
        specs = slot_cache_specs(cfg, mesh)
        new_kv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_kv.items()}
        new_sv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_sv.items()}
        tok = _mcon(mesh, tok, None)
    return tok, new_kv, new_sv


# ---------------------------------------------------------------------------
# disaggregated prefill/decode (DistServe, OSDI '24): prefill is
# compute-bound, decode is memory-bound — the serving gateway runs them
# on separate worker pools with a KV handoff in between. The two
# programs below are that handoff's device halves: ``prefill_detached``
# is ``prefill_slot`` minus the slot bank (it RETURNS the per-request
# KV block instead of scattering it), and ``inject_slot_kv`` is the
# scatter alone, run later on the decode worker's bank. Same forward
# graph, same sampler, same rng chain — so a prefill→handoff→decode
# request is bit-identical to the colocated path (tier-1-gated in
# tests/test_gateway.py).
# ---------------------------------------------------------------------------

def prefill_detached(cfg: LlamaConfig, params, tokens, true_len, rng,
                     temperature, top_k, top_p,
                     mesh: Optional[Mesh] = None):
    """Prefill ONE request without a slot bank: run the END-padded
    prompt (see :func:`prefill_slot` for why end padding is exact)
    through the cached stack and return the pieces a decode worker
    needs — ``(first_token (1,), k_block, v_block, new_rng)`` with
    k/v blocks shaped (L, n_kv_heads, bucket, hd). One compiled
    program per prompt bucket, exactly like ``prefill_slot``."""
    b, bucket = tokens.shape
    hd = cfg.head_dim
    tmp = {"k": jnp.zeros((cfg.n_layers, b, cfg.n_kv_heads, bucket,
                           hd), cfg.dtype),
           "v": jnp.zeros((cfg.n_layers, b, cfg.n_kv_heads, bucket,
                           hd), cfg.dtype),
           "pos": jnp.zeros((), jnp.int32)}
    true_len = jnp.asarray(true_len, jnp.int32)
    logits, tmp = _forward_cached(cfg, params, tokens, tmp, mesh=mesh,
                                  last_index=true_len - 1)
    rng, sub = jax.random.split(rng)
    tok = sample_logits(sub, logits[:, 0], temperature=temperature,
                        top_k=top_k, top_p=top_p)
    k_block, v_block = tmp["k"][:, 0], tmp["v"][:, 0]
    if mesh is not None:
        # the block leaves the device for the wire — replicate it so
        # the host gather is one copy, not a reshard
        tok = _mcon(mesh, tok, None)
        k_block = _mcon(mesh, k_block, None, None, None, None)
        v_block = _mcon(mesh, v_block, None, None, None, None)
    return tok, k_block, v_block, rng


def prefill_detached_chunk(cfg: LlamaConfig, params, chunk, cache,
                           true_len, rng, temperature, top_k, top_p,
                           mesh: Optional[Mesh] = None):
    """One chunk of a STREAMED detached prefill: run ``chunk`` (1, cw)
    — positions ``cache["pos"]`` .. ``pos+cw`` of the END-padded
    prompt — through the cached stack and return this chunk's
    just-computed K/V rows so the worker can ship their page frames
    over the wire WHILE the next chunk computes. Iterating this over
    the whole bucket is the same math as one :func:`prefill_detached`
    call: each position's attention masks the same causal prefix of
    the same bucket-sized cache, and the sampler splits the SAME
    request key once — so the streamed handoff stays bit-identical to
    the one-shot path (the disagg bit-identity gate covers it). One
    compiled program per (chunk width, bucket) pair.

    ``cache``: the (L, 1, n_kv_heads, bucket, hd) running buffers +
    ``pos``, carried across chunk calls (zeros at pos 0). Returns
    ``(tok (1,), k_chunk, v_chunk, new_rng, new_cache)`` with
    k/v_chunk shaped (L, n_kv_heads, cw, hd). ``tok``/``new_rng`` are
    meaningful only from the chunk containing position
    ``true_len - 1`` — the worker keeps that chunk's and discards the
    rest (later chunks sample from padding logits; harmless garbage,
    never emitted)."""
    b, cw = chunk.shape
    true_len = jnp.asarray(true_len, jnp.int32)
    # the last REAL position, local to this chunk (clamped: chunks
    # before/after the one holding true_len-1 sample garbage)
    li = jnp.clip(true_len - 1 - cache["pos"], 0, cw - 1)
    logits, cache = _forward_cached(cfg, params, chunk, cache,
                                    mesh=mesh, last_index=li)
    rng, sub = jax.random.split(rng)
    tok = sample_logits(sub, logits[:, 0], temperature=temperature,
                        top_k=top_k, top_p=top_p)
    pos0 = cache["pos"] - cw
    k_chunk = lax.dynamic_slice_in_dim(cache["k"][:, 0], pos0, cw,
                                       axis=2)
    v_chunk = lax.dynamic_slice_in_dim(cache["v"][:, 0], pos0, cw,
                                       axis=2)
    if mesh is not None:
        tok = _mcon(mesh, tok, None)
        k_chunk = _mcon(mesh, k_chunk, None, None, None, None)
        v_chunk = _mcon(mesh, v_chunk, None, None, None, None)
    return tok, k_chunk, v_chunk, rng, cache


def inject_slot_kv(cfg: LlamaConfig, k_block, v_block, true_len, slot,
                   token, rng, kv, sv, mesh: Optional[Mesh] = None):
    """Decode-side admission of a handed-off prefill: write the
    (L, n_kv_heads, bucket, hd) KV block into row ``slot`` of the slot
    bank and seed the slot's length/token/rng — the scatter half of
    :func:`prefill_slot`, with the forward pass already paid on the
    prefill pool. Pad K/V beyond ``true_len`` are excluded by the
    slot length mask and overwritten before the length reaches them
    (same argument as bucketed prefill). One compiled program per
    block bucket; kv is donatable. Returns (new_kv, new_sv)."""
    true_len = jnp.asarray(true_len, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    token = jnp.asarray(token, jnp.int32)
    z = jnp.zeros((), jnp.int32)
    new_kv = {
        "k": lax.dynamic_update_slice(
            kv["k"], k_block[:, None].astype(kv["k"].dtype),
            (z, slot, z, z, z)),
        "v": lax.dynamic_update_slice(
            kv["v"], v_block[:, None].astype(kv["v"].dtype),
            (z, slot, z, z, z)),
    }
    new_sv = {
        "lengths": lax.dynamic_update_slice(
            sv["lengths"].astype(jnp.int32), true_len[None], (slot,)),
        "tokens": lax.dynamic_update_slice(
            sv["tokens"], token[None].astype(sv["tokens"].dtype),
            (slot,)),
        "rngs": lax.dynamic_update_slice(
            sv["rngs"], rng[None].astype(sv["rngs"].dtype), (slot, z)),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding
        specs = slot_cache_specs(cfg, mesh)
        new_kv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_kv.items()}
        new_sv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_sv.items()}
    return new_kv, new_sv


# ---------------------------------------------------------------------------
# paged serving: fixed-size KV page pool + per-slot page tables
# (PagedAttention, Kwon et al. SOSP '23). The dense slot bank above
# reserves max_len KV per slot whether or not a request ever grows
# there; the paged variant keeps ONE flat pool of (n_pages, kvh,
# page_size, hd) pages per layer and maps each slot's logical sequence
# through an int32 page-table row the host owns. Admission is bounded
# by free PAGES, not slots, and read-only pages can be shared between
# slots (refcounted copy-on-write prefix sharing — the allocator lives
# in ``mxtpu.serve.engine``; these are its device halves). Page 0 is
# scratch: the engine never hands it out, zeroed table rows alias it,
# and redirected writes land there harmlessly.
# ---------------------------------------------------------------------------

def paged_cache_specs(cfg: LlamaConfig, mesh: Mesh):
    """PartitionSpecs for the paged pool: kv heads over tp (axis 2 of
    the (L, n_pages, kvh, page_size, hd) pool — same head-axis rule as
    :func:`slot_cache_specs`), page axis unsharded (the host scatters
    single pages). Scale pools (int8 mode) follow the same spec."""
    tp = ("tp" if "tp" in mesh.axis_names
          and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None)
    kv = P(None, None, tp) if tp is not None else P()
    return {"k": kv, "v": kv, "ks": kv, "vs": kv,
            "lengths": P(), "tokens": P(), "rngs": P()}


def init_paged_cache(cfg: LlamaConfig, max_slots: int, n_pages: int,
                     page_size: int, mesh: Optional[Mesh] = None,
                     int8: bool = False):
    """Device state for the PAGED serving engine: per-layer K/V pools
    of (L, n_pages, n_kv_heads, page_size, hd) plus the same per-slot
    ``lengths``/``tokens``/``rngs`` vectors as :func:`init_slot_cache`
    (page tables stay HOST-side — a small int32 operand per step, so
    table edits never touch device state). ``int8=True`` stores the
    pools as int8 with per-token-per-head f32 scales ``ks``/``vs`` of
    (L, n_pages, kvh, page_size) — KV HBM halves again; dequant happens
    on gather (deterministic, not bit-exact with the f32 pool —
    docs/serving.md)."""
    hd = cfg.head_dim
    shape = (cfg.n_layers, n_pages, cfg.n_kv_heads, page_size, hd)

    def build():
        if int8:
            pools = {"k": jnp.zeros(shape, jnp.int8),
                     "v": jnp.zeros(shape, jnp.int8),
                     "ks": jnp.ones(shape[:4], jnp.float32),
                     "vs": jnp.ones(shape[:4], jnp.float32)}
        else:
            pools = {"k": jnp.zeros(shape, cfg.dtype),
                     "v": jnp.zeros(shape, cfg.dtype)}
        pools.update({
            "lengths": jnp.zeros((max_slots,), jnp.int32),
            "tokens": jnp.zeros((max_slots,), jnp.int32),
            "rngs": jnp.zeros((max_slots, 2), jnp.uint32)})
        return pools

    if mesh is None:
        return build()
    from jax.sharding import NamedSharding
    specs = paged_cache_specs(cfg, mesh)
    shardings = {n: NamedSharding(mesh, specs[n]) for n in build()}
    return jax.jit(build, out_shardings=shardings)()


def _q8_token(x):
    """Per-token-per-head symmetric int8: scale over the hd axis."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, s


def _gather_slot_pages(pool, scales, pages_row, dt):
    """One slot's pages → a contiguous (L, kvh, cap, hd) cache view.
    pool: (L, n_pages, kvh, ps, hd); pages_row: (P,) int32."""
    g = jnp.take(pool, pages_row, axis=1)        # (L, P, kvh, ps, hd)
    if scales is not None:
        sc = jnp.take(scales, pages_row, axis=1)  # (L, P, kvh, ps)
        g = g.astype(jnp.float32) * sc[..., None]
    L, Pn, hkv, ps, hd = g.shape
    return (g.transpose(0, 2, 1, 3, 4)
             .reshape(L, hkv, Pn * ps, hd).astype(dt))


def _layer_slots_paged(cfg: LlamaConfig, cos, sin, pos, phys, off,
                       page_table, mesh, kvspec, x, lp, ck, cv,
                       cks=None, cvs=None):
    """One block of the PAGED slot decode: x (S, 1, dim); ck/cv are the
    per-layer page POOLS (n_pages, kvh, ps, hd). Each slot's new K/V
    scatters into pool page ``phys[i]`` at in-page offset ``off[i]``
    (the host redirects inactive slots to scratch page 0 — their table
    rows are zeroed, so no live page can alias the write), then the
    slot attends its gathered pages via the length-masked paged
    kernel."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _wq8(lp["wq"], dt)).reshape(b, s, cfg.n_heads, hd)
    k = (h @ _wq8(lp["wk"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (h @ _wq8(lp["wv"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)          # (S, h, 1, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    head_ax = (kvspec[1] if kvspec is not None and len(kvspec) > 1
               else None)
    q = _mcon(mesh, q, None, head_ax, None, None)
    k = _mcon(mesh, k, None, head_ax, None, None)
    v = _mcon(mesh, v, None, head_ax, None, None)

    knew = k[:, :, 0, :]                 # (S, kvh, hd)
    vnew = v[:, :, 0, :]
    if cks is not None:                  # int8 pool: quantize the write
        kq, ksc = _q8_token(knew)
        vq, vsc = _q8_token(vnew)
        ck = ck.at[phys, :, off, :].set(kq)
        cv = cv.at[phys, :, off, :].set(vq)
        cks = cks.at[phys, :, off].set(ksc)
        cvs = cvs.at[phys, :, off].set(vsc)
        kf = _gather_slot_pages_batch(ck, cks, page_table, dt)
        vf = _gather_slot_pages_batch(cv, cvs, page_table, dt)
        o = slot_decode_attention(q, kf, vf, pos + 1)
    else:
        ck = ck.at[phys, :, off, :].set(knew.astype(ck.dtype))
        cv = cv.at[phys, :, off, :].set(vnew.astype(cv.dtype))
        if mesh is not None:
            from jax.sharding import NamedSharding
            ck = lax.with_sharding_constraint(
                ck, NamedSharding(mesh, kvspec))
            cv = lax.with_sharding_constraint(
                cv, NamedSharding(mesh, kvspec))
        o = paged_decode_attention(q, ck, cv, page_table, pos + 1)

    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    x = x + _mcon(mesh, o @ _wq8(lp["wo"], dt), None, None, None)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    delta, _ = _ffn(cfg, lp, h, mesh, serving=True)
    x = x + _mcon(mesh, delta, None, None, None)
    if cks is not None:
        return x, ck, cv, cks, cvs
    return x, ck, cv


def _gather_slot_pages_batch(pool, scales, page_table, dt):
    """All slots' pages → (S, kvh, cap, hd) with int8 dequant on the
    gathered bytes (the whole-pool dequant would undo the HBM win)."""
    # pool here is PER-LAYER: (n_pages, kvh, ps, hd); page_table is
    # (S, P) so the take yields (S, P, kvh, ps, hd)
    g = jnp.take(pool, page_table, axis=0)
    sc = jnp.take(scales, page_table, axis=0)     # (S, P, kvh, ps)
    g = g.astype(jnp.float32) * sc[..., None]
    S, Pn, hkv, ps, hd = g.shape
    return (g.transpose(0, 2, 1, 3, 4)
             .reshape(S, hkv, Pn * ps, hd).astype(dt))


def decode_slots_paged(cfg: LlamaConfig, params, kv, sv, active,
                       page_table, temperature, top_k, top_p,
                       mesh: Optional[Mesh] = None):
    """ONE decode step over the PAGED bank — :func:`decode_slots` with
    the dense (slot, max_len) cache row replaced by a page-table
    indirection. ``page_table`` (S, pages_per_slot) int32 is a small
    per-step operand (host-owned: admission edits tables without
    touching device state, and the jit cache key never changes).
    Inactive slots carry zeroed table rows, so their cache write lands
    in scratch page 0 and their (discarded) sample reads scratch —
    active slots' pages are never aliased. Sampling, rng chains, and
    the length mask are IDENTICAL to the dense path, which is what
    keeps paged serving bit-identical to per-request ``generate``
    (asserted in tests/test_paged_kv.py). kv: the pool dict from
    :func:`init_paged_cache` minus the per-slot vectors (donatable);
    sv as in :func:`decode_slots`."""
    int8 = "ks" in kv
    ps = kv["k"].shape[3]
    cap = page_table.shape[1] * ps
    lengths = sv["lengths"].astype(jnp.int32)
    pos = jnp.minimum(lengths, cap - 1)       # per-slot write position
    nslots = page_table.shape[0]
    phys = page_table[jnp.arange(nslots), pos // ps]  # (S,) pool index
    off = pos % ps
    tokens = sv["tokens"][:, None]
    emb = params["tok_embed"]
    if isinstance(emb, dict):
        x = emb["q8"][tokens].astype(cfg.dtype) * \
            emb["s8"][0].astype(cfg.dtype)
    else:
        x = emb[tokens].astype(cfg.dtype)

    kvspec = None
    if mesh is not None:
        kvspec = P(*tuple(paged_cache_specs(cfg, mesh)["k"])[1:])
    cos_t, sin_t = rope_tables(cfg, cap)
    cos = cos_t[pos][:, None, None, :]        # (S, 1, 1, hd/2)
    sin = sin_t[pos][:, None, None, :]

    if int8:
        def body(x, xs):
            lp, ck, cv, cks, cvs = xs
            x, ck, cv, cks, cvs = _layer_slots_paged(
                cfg, cos, sin, pos, phys, off, page_table, mesh,
                kvspec, x, lp, ck, cv, cks, cvs)
            return x, (ck, cv, cks, cvs)
        x, (ck, cv, cks, cvs) = lax.scan(
            body, x, (params["layers"], kv["k"], kv["v"],
                      kv["ks"], kv["vs"]))
        new_kv = {"k": ck, "v": cv, "ks": cks, "vs": cvs}
    else:
        def body(x, xs):
            lp, ck, cv = xs
            x, ck, cv = _layer_slots_paged(
                cfg, cos, sin, pos, phys, off, page_table, mesh,
                kvspec, x, lp, ck, cv)
            return x, (ck, cv)
        x, (ck, cv) = lax.scan(body, x,
                               (params["layers"], kv["k"], kv["v"]))
        if mesh is not None:
            from jax.sharding import NamedSharding
            full = NamedSharding(mesh, paged_cache_specs(cfg, mesh)["k"])
            ck = lax.with_sharding_constraint(ck, full)
            cv = lax.with_sharding_constraint(cv, full)
        new_kv = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    hw = (_wq8(params["tok_embed"], cfg.dtype).T if cfg.tie_embeddings
          else _wq8(params["lm_head"], cfg.dtype))
    logits = jnp.einsum("bsd,dv->bsv", x, hw,
                        preferred_element_type=jnp.float32)[:, 0]

    def one(key, lg, t, kk, pp):
        key, sub = jax.random.split(key)
        tok = sample_logits(sub, lg[None], temperature=t,
                            top_k=kk, top_p=pp)[0]
        return key, tok

    new_rngs, sampled = jax.vmap(one)(
        sv["rngs"], logits, temperature, top_k, top_p)
    new_lengths = lengths + active.astype(jnp.int32)
    if mesh is not None:
        sampled = _mcon(mesh, sampled, None)
        new_lengths = _mcon(mesh, new_lengths, None)
        new_rngs = _mcon(mesh, new_rngs, None, None)
    return sampled, new_kv, \
        {"lengths": new_lengths, "tokens": sampled, "rngs": new_rngs}


def _scatter_slot_pages(kv, pages_row, tmp_k, tmp_v, prefix_len,
                        bucket, int8):
    """Write a slot's contiguous (L, 1, kvh, cap, hd) cache view back
    into the pools at its pages. In f32/bf16 mode the WHOLE view is
    scattered — shared prefix pages are rewritten with bit-identical
    content (the gather/forward never modified them) and duplicate
    scratch indices in ``pages_row`` collapse onto page 0, which is
    never attended. In int8 mode only the freshly written span
    [prefix_len, prefix_len+bucket) is re-quantized; untouched
    positions keep their RAW stored bytes — quantize∘dequant is not
    idempotent, so round-tripping shared pages would corrupt them."""
    L, _, hkv, cap, hd = tmp_k.shape
    ps = kv["k"].shape[3]
    Pn = pages_row.shape[0]

    def to_pages(a):                      # (L, kvh, cap, hd) → pages
        return (a.reshape(L, hkv, Pn, ps, hd)
                 .transpose(0, 2, 1, 3, 4))

    kd, vd = tmp_k[:, 0], tmp_v[:, 0]     # (L, kvh, cap, hd)
    out = dict(kv)
    if int8:
        kq, ksc = _q8_token(kd)           # (L, kvh, cap, hd)/(L,kvh,cap)
        vq, vsc = _q8_token(vd)
        written = ((jnp.arange(cap) >= prefix_len) &
                   (jnp.arange(cap) < prefix_len + bucket))
        old_k = _gather_pages_raw(kv["k"], pages_row)   # (L, kvh, cap, hd)
        old_v = _gather_pages_raw(kv["v"], pages_row)
        old_ks = _gather_pages_raw(kv["ks"], pages_row)
        old_vs = _gather_pages_raw(kv["vs"], pages_row)
        kq = jnp.where(written[None, None, :, None], kq, old_k)
        vq = jnp.where(written[None, None, :, None], vq, old_v)
        ksc = jnp.where(written[None, None, :], ksc, old_ks)
        vsc = jnp.where(written[None, None, :], vsc, old_vs)
        out["k"] = kv["k"].at[:, pages_row].set(to_pages(kq))
        out["v"] = kv["v"].at[:, pages_row].set(to_pages(vq))
        sc_pages = lambda a: (a.reshape(L, hkv, Pn, ps)
                               .transpose(0, 2, 1, 3))
        out["ks"] = kv["ks"].at[:, pages_row].set(sc_pages(ksc))
        out["vs"] = kv["vs"].at[:, pages_row].set(sc_pages(vsc))
    else:
        out["k"] = kv["k"].at[:, pages_row].set(
            to_pages(kd.astype(kv["k"].dtype)))
        out["v"] = kv["v"].at[:, pages_row].set(
            to_pages(vd.astype(kv["v"].dtype)))
    return out


def _gather_pages_raw(pool, pages_row):
    """(L, n_pages, kvh, ps[, hd]) pool → contiguous (L, kvh, cap[,
    hd]) view of one slot's pages, NO dequant (raw stored bytes)."""
    g = jnp.take(pool, pages_row, axis=1)
    if g.ndim == 5:
        L, Pn, hkv, ps, hd = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(L, hkv, Pn * ps, hd)
    L, Pn, hkv, ps = g.shape
    return g.transpose(0, 2, 1, 3).reshape(L, hkv, Pn * ps)


def prefill_slot_paged(cfg: LlamaConfig, params, tokens, true_len,
                       prefix_len, pages_row, slot, kv, sv, rng,
                       temperature, top_k, top_p,
                       mesh: Optional[Mesh] = None):
    """Paged admission, cold OR warm: gather the slot's pages into a
    contiguous cache view, run the SUFFIX tokens (END-padded to their
    bucket) through the cached stack at ``pos=prefix_len``, scatter the
    pages back, seed the slot vectors, and sample the first generated
    token.

    Warm admission (``prefix_len > 0``) is what prefix sharing buys:
    the shared pages already hold positions [0, prefix_len), the
    suffix attends them through the causal mask exactly as
    ``chunked_prefill`` attends an earlier chunk (the established
    bit-identity property), and only ``len(prompt) - prefix_len``
    tokens pay forward FLOPs — the TTFT win. Cold admission is the
    same program at ``prefix_len=0``. One compiled program per SUFFIX
    bucket (the same power-of-two set as dense prefill, so the
    compile bound is unchanged).

    tokens: (1, bucket) suffix; true_len: TOTAL valid length
    (prefix + real suffix); pages_row: (pages_per_slot,) int32 — the
    slot's full table row (scratch-0 tail entries collapse onto the
    never-attended page 0). The engine guarantees write range
    [prefix_len, prefix_len+bucket) stays inside the row's capacity
    and that every page it touches is PRIVATE (CoW forked). Returns
    (first token (1,), new kv pools, new sv)."""
    b, bucket = tokens.shape
    int8 = "ks" in kv
    dt = cfg.dtype
    true_len = jnp.asarray(true_len, jnp.int32)
    prefix_len = jnp.asarray(prefix_len, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    tmp = {"k": _gather_slot_pages(kv["k"], kv.get("ks"), pages_row,
                                   dt)[:, None],
           "v": _gather_slot_pages(kv["v"], kv.get("vs"), pages_row,
                                   dt)[:, None],
           "pos": prefix_len}
    logits, tmp = _forward_cached(cfg, params, tokens, tmp, mesh=mesh,
                                  last_index=true_len - prefix_len - 1)
    rng, sub = jax.random.split(rng)
    tok = sample_logits(sub, logits[:, 0], temperature=temperature,
                        top_k=top_k, top_p=top_p)
    new_kv = _scatter_slot_pages(kv, pages_row, tmp["k"], tmp["v"],
                                 prefix_len, bucket, int8)
    z = jnp.zeros((), jnp.int32)
    new_sv = {
        "lengths": lax.dynamic_update_slice(
            sv["lengths"].astype(jnp.int32), true_len[None], (slot,)),
        "tokens": lax.dynamic_update_slice(
            sv["tokens"], tok.astype(sv["tokens"].dtype), (slot,)),
        "rngs": lax.dynamic_update_slice(
            sv["rngs"], rng[None].astype(sv["rngs"].dtype), (slot, z)),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding
        specs = paged_cache_specs(cfg, mesh)
        new_kv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_kv.items()}
        new_sv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_sv.items()}
        tok = _mcon(mesh, tok, None)
    return tok, new_kv, new_sv


def inject_paged_kv(cfg: LlamaConfig, k_block, v_block, true_len,
                    pages_row, slot, token, rng, kv, sv,
                    mesh: Optional[Mesh] = None):
    """Decode-side admission of a handed-off prefill into the PAGED
    bank: split the (L, n_kv_heads, bucket, hd) block into page_size
    chunks and scatter them at the slot's first ceil(bucket/ps) pages —
    :func:`inject_slot_kv`'s role for the paged layout. Pad K/V beyond
    ``true_len`` land in pages the slot owns and are excluded by its
    length mask (same argument as the dense path). In int8 mode the
    block is quantized per token on the way in. kv donatable. Returns
    (new kv pools, new sv)."""
    int8 = "ks" in kv
    ps = kv["k"].shape[3]
    L, hkv, bucket, hd = k_block.shape
    n_blk = -(-bucket // ps)              # pages the block spans
    pad = n_blk * ps - bucket
    if pad:
        k_block = jnp.pad(k_block, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_block = jnp.pad(v_block, ((0, 0), (0, 0), (0, pad), (0, 0)))
    true_len = jnp.asarray(true_len, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    token = jnp.asarray(token, jnp.int32)
    dst = pages_row[:n_blk]

    def to_pages(a):                      # (L, kvh, nP·ps, hd) → pages
        return (a.reshape(L, hkv, n_blk, ps, hd)
                 .transpose(0, 2, 1, 3, 4))

    out = dict(kv)
    if int8:
        kq, ksc = _q8_token(k_block)
        vq, vsc = _q8_token(v_block)
        out["k"] = kv["k"].at[:, dst].set(to_pages(kq))
        out["v"] = kv["v"].at[:, dst].set(to_pages(vq))
        sc_pages = lambda a: (a.reshape(L, hkv, n_blk, ps)
                               .transpose(0, 2, 1, 3))
        out["ks"] = kv["ks"].at[:, dst].set(sc_pages(ksc))
        out["vs"] = kv["vs"].at[:, dst].set(sc_pages(vsc))
    else:
        out["k"] = kv["k"].at[:, dst].set(
            to_pages(k_block.astype(kv["k"].dtype)))
        out["v"] = kv["v"].at[:, dst].set(
            to_pages(v_block.astype(kv["v"].dtype)))
    z = jnp.zeros((), jnp.int32)
    new_sv = {
        "lengths": lax.dynamic_update_slice(
            sv["lengths"].astype(jnp.int32), true_len[None], (slot,)),
        "tokens": lax.dynamic_update_slice(
            sv["tokens"], token[None].astype(sv["tokens"].dtype),
            (slot,)),
        "rngs": lax.dynamic_update_slice(
            sv["rngs"], rng[None].astype(sv["rngs"].dtype), (slot, z)),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding
        specs = paged_cache_specs(cfg, mesh)
        out = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n])) for n, a in out.items()}
        new_sv = {n: lax.with_sharding_constraint(
            a, NamedSharding(mesh, specs[n]))
            for n, a in new_sv.items()}
    return out, new_sv


def copy_page(kv, src, dst):
    """Copy pool page ``src`` onto page ``dst`` across every pool array
    — the engine's copy-on-write fork primitive (one compiled program
    for any src/dst: both are traced scalars). Only pool arrays (page
    axis 1) are touched; per-slot vectors pass through untouched."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = dict(kv)
    for n in ("k", "v", "ks", "vs"):
        if n in kv:
            a = kv[n]
            page = lax.dynamic_index_in_dim(a, src, axis=1,
                                            keepdims=False)
            out[n] = lax.dynamic_update_index_in_dim(a, page, dst,
                                                     axis=1)
    return out


# ---------------------------------------------------------------------------
# Speculative decoding (ISSUE 19): one batched VERIFY forward over each
# slot's current token plus its k drafted tokens against the paged
# pool, with a bit-exact accept oracle — a drafted token is accepted
# iff it is IDENTICAL to what the target rng chain would emit
# (Leviathan et al. 2023, specialized to exact-match acceptance so the
# served stream is bit-identical to per-request ``generate`` by
# construction, not merely distribution-preserving). Drafting itself is
# host-side (the engine's prompt/n-gram lookup, or a small draft model
# later) — this file only holds the device half.
# ---------------------------------------------------------------------------

def _layer_slots_spec(cfg: LlamaConfig, cos, sin, qlen, phys, off,
                      page_table, mesh, kvspec, x, lp, ck, cv,
                      cks=None, cvs=None):
    """One block of the SPECULATIVE paged decode: x (S, W, dim) holds
    each slot's current token plus its drafted run (W = k + 1). Token
    i of slot s scatters its K/V into pool page ``phys[s, i]`` at
    offset ``off[s, i]`` (the host redirects out-of-budget positions
    and inactive slots to scratch page 0), then attends its OWN causal
    prefix ``[0, qlen[s, i])`` — the per-query length mask that keeps
    every drafted position's logits exactly what a sequential decode
    at that position would compute."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _wq8(lp["wq"], dt)).reshape(b, s, cfg.n_heads, hd)
    k = (h @ _wq8(lp["wk"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (h @ _wq8(lp["wv"], dt)).reshape(b, s, cfg.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)          # (S, h, W, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    head_ax = (kvspec[1] if kvspec is not None and len(kvspec) > 1
               else None)
    q = _mcon(mesh, q, None, head_ax, None, None)
    k = _mcon(mesh, k, None, head_ax, None, None)
    v = _mcon(mesh, v, None, head_ax, None, None)

    knew = k.transpose(0, 2, 1, 3)       # (S, W, kvh, hd)
    vnew = v.transpose(0, 2, 1, 3)
    if cks is not None:                  # int8 pool: quantize the write
        kq, ksc = _q8_token(knew)
        vq, vsc = _q8_token(vnew)
        ck = ck.at[phys, :, off, :].set(kq)
        cv = cv.at[phys, :, off, :].set(vq)
        cks = cks.at[phys, :, off].set(ksc)
        cvs = cvs.at[phys, :, off].set(vsc)
        kf = _gather_slot_pages_batch(ck, cks, page_table, dt)
        vf = _gather_slot_pages_batch(cv, cvs, page_table, dt)
        o = slot_decode_attention(q, kf, vf, qlen)
    else:
        ck = ck.at[phys, :, off, :].set(knew.astype(ck.dtype))
        cv = cv.at[phys, :, off, :].set(vnew.astype(cv.dtype))
        if mesh is not None:
            from jax.sharding import NamedSharding
            ck = lax.with_sharding_constraint(
                ck, NamedSharding(mesh, kvspec))
            cv = lax.with_sharding_constraint(
                cv, NamedSharding(mesh, kvspec))
        o = paged_decode_attention(q, ck, cv, page_table, qlen)

    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
    x = x + _mcon(mesh, o @ _wq8(lp["wo"], dt), None, None, None)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    delta, _ = _ffn(cfg, lp, h, mesh, serving=True)
    x = x + _mcon(mesh, delta, None, None, None)
    if cks is not None:
        return x, ck, cv, cks, cvs
    return x, ck, cv


def decode_slots_spec(cfg: LlamaConfig, params, kv, sv, active,
                      page_table, drafts, temperature, top_k, top_p,
                      mesh: Optional[Mesh] = None):
    """ONE speculative decode step over the PAGED bank: feed each
    slot's current token plus its ``k`` drafted tokens (W = k + 1
    positions) through a single batched target forward, then run the
    exact-match accept oracle down each slot's rng chain.

    Emission i+1 of a slot is ``sample_logits`` of the logits after
    position pos+i, drawn with the SAME split-discipline as
    :func:`decode_slots_paged` (one ``jax.random.split`` per VALID
    emission — rejected positions never advance the chain, so
    ``serve.resume_key(seed, n_emitted)`` stays exact under
    multi-token emission). Emission i+1 is valid iff every earlier
    draft matched its emission exactly; the number of valid emissions
    per step is therefore 1..W (the plain decode emission always
    lands). Rejected-suffix KV is "rolled back" by simply not
    advancing ``lengths`` past the accepted run: the garbage K/V
    beyond the new length is excluded by every later length mask and
    overwritten in place by the next step's writes — no page is ever
    freed or re-granted mid-run (page refcounts are the host's and
    never change here).

    drafts: (S, k) int32, entry < 0 = no draft at that position (a
    draftless slot emits exactly 1 token, bit-matching the plain
    step). page_table as in :func:`decode_slots_paged` — inactive
    slots carry zeroed rows so all their writes land in scratch page
    0; writes past the table's capacity are redirected to scratch
    rather than clamped (a clamp would corrupt the slot's last live
    page). Returns (toks (S, W) int32, emits (S, W) bool, new kv,
    new sv): the engine emits ``toks[s, :emits[s].sum()]``."""
    int8 = "ks" in kv
    ps = kv["k"].shape[3]
    cap = page_table.shape[1] * ps
    S, K = drafts.shape
    W = K + 1
    lengths = sv["lengths"].astype(jnp.int32)
    pos = jnp.minimum(lengths, cap - 1)
    wpos = pos[:, None] + jnp.arange(W)[None, :]      # (S, W)
    oob = wpos >= cap
    cw = jnp.minimum(wpos, cap - 1)                   # safe gather idx
    rows = jnp.arange(S)[:, None]
    phys = jnp.where(oob, 0, page_table[rows, cw // ps])
    off = cw % ps
    qlen = wpos + 1                       # query i attends [0, pos+i+1)

    toks_in = jnp.concatenate(
        [sv["tokens"][:, None], drafts.astype(sv["tokens"].dtype)],
        axis=1)
    emb = params["tok_embed"]
    if isinstance(emb, dict):
        x = emb["q8"][toks_in].astype(cfg.dtype) * \
            emb["s8"][0].astype(cfg.dtype)
    else:
        x = emb[toks_in].astype(cfg.dtype)

    kvspec = None
    if mesh is not None:
        kvspec = P(*tuple(paged_cache_specs(cfg, mesh)["k"])[1:])
    cos_t, sin_t = rope_tables(cfg, cap)
    cos = cos_t[cw][:, None]              # (S, 1, W, hd/2)
    sin = sin_t[cw][:, None]

    if int8:
        def body(x, xs):
            lp, ck, cv, cks, cvs = xs
            x, ck, cv, cks, cvs = _layer_slots_spec(
                cfg, cos, sin, qlen, phys, off, page_table, mesh,
                kvspec, x, lp, ck, cv, cks, cvs)
            return x, (ck, cv, cks, cvs)
        x, (ck, cv, cks, cvs) = lax.scan(
            body, x, (params["layers"], kv["k"], kv["v"],
                      kv["ks"], kv["vs"]))
        new_kv = {"k": ck, "v": cv, "ks": cks, "vs": cvs}
    else:
        def body(x, xs):
            lp, ck, cv = xs
            x, ck, cv = _layer_slots_spec(
                cfg, cos, sin, qlen, phys, off, page_table, mesh,
                kvspec, x, lp, ck, cv)
            return x, (ck, cv)
        x, (ck, cv) = lax.scan(body, x,
                               (params["layers"], kv["k"], kv["v"]))
        if mesh is not None:
            from jax.sharding import NamedSharding
            full = NamedSharding(mesh, paged_cache_specs(cfg, mesh)["k"])
            ck = lax.with_sharding_constraint(ck, full)
            cv = lax.with_sharding_constraint(cv, full)
        new_kv = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    hw = (_wq8(params["tok_embed"], cfg.dtype).T if cfg.tie_embeddings
          else _wq8(params["lm_head"], cfg.dtype))
    logits = jnp.einsum("bsd,dv->bsv", x, hw,
                        preferred_element_type=jnp.float32)   # (S, W, V)

    # accept oracle: scan the W per-position logits down the slot's rng
    # chain. ok carries "all earlier drafts matched"; the key advances
    # ONLY on a valid emission (exactly one split per emitted token).
    nxt = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.full((S, 1), -1, jnp.int32)],
        axis=1)                           # draft verified by emission i
    has = nxt >= 0

    def one(key, lgs, nx, hs, t, kk, pp):
        def step(carry, inp):
            key, ok = carry
            lg, nd, h = inp
            key2, sub = jax.random.split(key)
            tok = sample_logits(sub, lg[None], temperature=t,
                                top_k=kk, top_p=pp)[0]
            emit = ok
            key = jnp.where(emit, key2, key)
            ok = ok & h & (tok == nd)
            return (key, ok), (tok, emit)
        (key, _), (tk, em) = lax.scan(
            step, (key, jnp.bool_(True)), (lgs, nx, hs))
        return key, tk, em

    new_rngs, toks, emits = jax.vmap(one)(
        sv["rngs"], logits, nxt, has, temperature, top_k, top_p)
    # dtype pinned: under x64 a default integer sum promotes to int64,
    # which would flip the lengths dtype and retrace every program
    n_emit = jnp.sum(emits, axis=1, dtype=jnp.int32)  # (S,) in 1..W
    new_lengths = lengths + n_emit * active.astype(jnp.int32)
    last = jnp.take_along_axis(
        toks, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
    if mesh is not None:
        toks = _mcon(mesh, toks, None, None)
        emits = _mcon(mesh, emits, None, None)
        last = _mcon(mesh, last, None)
        new_lengths = _mcon(mesh, new_lengths, None)
        new_rngs = _mcon(mesh, new_rngs, None, None)
    return toks, emits, new_kv, \
        {"lengths": new_lengths, "tokens": last, "rngs": new_rngs}
