"""mx.rtc — runtime-compiled user kernels (reference ``src/common/rtc.cc``
``mx.rtc.CudaModule`` over NVRTC [path cites — unverified]).

TPU rebuild: the user-supplied kernel language is **Pallas** (Mosaic)
instead of CUDA C — same role, hardware-idiomatic form:

    import mxtpu as mx
    from jax.experimental import pallas as pl

    def scale_add(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]

    mod = mx.rtc.PallasModule()
    kern = mod.compile("scale_add", scale_add)
    out = kern.launch(x, y)                       # NDArrays in/out

``jax_kernel`` wraps any jax-traceable python function as an op (the
analogue of the reference's 1.6 pointwise-fusion RTC path), with
autograd support through the shared apply_op funnel; custom VJPs come
along for free via ``jax.custom_vjp`` on the wrapped function.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.ndarray import apply_op

__all__ = ["PallasModule", "PallasKernel", "jax_kernel", "CudaModule"]


def jax_kernel(fn: Callable, name: Optional[str] = None) -> Callable:
    """Wrap a jax-traceable function into an NDArray op (tape-aware,
    hybridize-compatible). ``fn`` takes/returns jax arrays."""
    opname = name or getattr(fn, "__name__", "jax_kernel")

    def op(*arrays, **kwargs):
        raw = fn if not kwargs else (lambda *xs: fn(*xs, **kwargs))
        out = apply_op(raw, list(arrays), opname)
        return out
    op.__name__ = opname
    return op


class PallasKernel:
    """One compiled Pallas kernel (the reference's CudaModule.Kernel)."""

    def __init__(self, name: str, kernel_fn: Callable,
                 grid=None, in_specs=None, out_specs=None,
                 interpret: bool = False):
        self.name = name
        self._kernel_fn = kernel_fn
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._interpret = interpret

    def launch(self, *arrays, out_shape=None, out_dtype=None):
        """Run on NDArrays. ``out_shape``/``out_dtype`` default to the
        first input's (elementwise-kernel convention)."""
        from jax.experimental import pallas as pl
        if not arrays:
            raise MXNetError("launch needs at least one input array")
        shape = tuple(out_shape) if out_shape is not None \
            else arrays[0].shape
        dtype = out_dtype if out_dtype is not None else arrays[0].dtype
        kwargs: Dict[str, Any] = {}
        if self._grid is not None:
            kwargs["grid"] = self._grid
        if self._in_specs is not None:
            kwargs["in_specs"] = self._in_specs
        if self._out_specs is not None:
            kwargs["out_specs"] = self._out_specs
        if self._interpret:
            kwargs["interpret"] = True
        call = pl.pallas_call(
            self._kernel_fn,
            out_shape=jax.ShapeDtypeStruct(shape, dtype), **kwargs)
        return apply_op(lambda *xs: call(*xs), list(arrays),
                        f"pallas[{self.name}]")


class PallasModule:
    """A named collection of user kernels (reference ``CudaModule``:
    compile once, get_kernel by name, launch on arrays)."""

    def __init__(self, interpret: bool = False):
        self._kernels: Dict[str, PallasKernel] = {}
        self._interpret = interpret

    def compile(self, name: str, kernel_fn: Callable, grid=None,
                in_specs=None, out_specs=None) -> PallasKernel:
        k = PallasKernel(name, kernel_fn, grid, in_specs, out_specs,
                         interpret=self._interpret)
        self._kernels[name] = k
        return k

    def get_kernel(self, name: str, signature: str = "") -> PallasKernel:
        if name not in self._kernels:
            raise MXNetError(f"kernel {name!r} not compiled in this "
                             f"module (have: {sorted(self._kernels)})")
        return self._kernels[name]


class CudaModule:
    """Reference-compat shim: CUDA C source cannot run on TPU hardware;
    points users at the Pallas path."""

    def __init__(self, source=None, options=(), exports=()):
        raise MXNetError(
            "CUDA RTC is not available on TPU. Write the kernel as a "
            "Pallas function and use mx.rtc.PallasModule (same "
            "compile/get_kernel/launch flow), or wrap plain jax code "
            "with mx.rtc.jax_kernel.")
