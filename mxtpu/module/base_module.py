"""BaseModule: the epoch-loop trainer contract (reference
``python/mxnet/module/base_module.py`` [path cite — unverified]).

``fit()`` is the reference's symbolic training loop: bind → init params
→ init optimizer → per-batch forward/backward/update + metric, with
callbacks. On TPU the per-batch body is two jitted XLA programs
(Executor fwd / fwd+bwd) and the optimizer update; batches stream in
through the async PJRT queue so host-side iteration overlaps compute.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, List, Optional

from .. import metric as _metric
from ..base import MXNetError


class BaseModule:
    """Abstract module: high-level (fit/score/predict) over the
    intermediate (forward/backward/update) API."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.symbol = None

    # -- to implement -------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    # -- derived high-level API ---------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """Run inference over ``eval_data``, accumulating ``eval_metric``."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        """Forward over a whole iterator, returning concatenated outputs."""
        from .. import ndarray as nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs_list: List[List] = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs_list.append(outs)
        if not outputs_list:
            return []
        if merge_batches:
            n_out = len(outputs_list[0])
            merged = [nd.concat(*[b[i] for b in outputs_list], dim=0)
                      for i in range(n_out)]
            return merged[0] if n_out == 1 else merged
        return outputs_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The reference's training loop (Module.fit, SURVEY.md §3.3)."""
        assert num_epoch is not None, "num_epoch is required for fit"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch, nbatch, eval_metric,
                                         locals()))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)


class BatchEndParam:
    """Callback payload (reference namedtuple BatchEndParam)."""

    def __init__(self, epoch, nbatch, eval_metric, locals_=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals_


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
