"""Module: symbolic training over a bound Executor (reference
``python/mxnet/module/module.py`` + ``executor_group.py`` [path cites —
unverified]).

The reference's DataParallelExecutorGroup sliced each batch over a GPU
list; here ONE executor runs the whole batch as one XLA program — multi-
chip data parallelism is mesh sharding (mxtpu.parallel), not executor
replication, so ``context`` lists collapse to their first entry.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .. import initializer as _init
from .. import ndarray as nd
from .. import optimizer as _opt
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..model import save_checkpoint as _save_checkpoint
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    """Train/predict a Symbol (reference ``mx.mod.Module``)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self.symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        if isinstance(context, (list, tuple)):
            context = context[0] if context else None
        self._context = context or current_context()
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names and
                             n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._preload_opt_states = None

    # -- binding ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        shapes = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                name, shape = desc[0], desc[1]
                shapes[name] = tuple(shape)
        req: Dict[str, str] = {}
        for name in self.symbol.list_arguments():
            if name in self._data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or \
                    name in self._fixed_param_names or not for_training:
                req[name] = "null"
            else:
                req[name] = grad_req
        self._exec = self.symbol.simple_bind(self._context, grad_req=req,
                                             **shapes)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.binded = True

    # -- parameters ----------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded, "call bind before init_params"
        if self.params_initialized and not force_init:
            return
        if arg_params is None and getattr(self, "_preloaded_params", None):
            arg_params, aux_params = self._preloaded_params
        initializer = initializer if initializer is not None \
            else _init.Uniform(0.01)
        if isinstance(initializer, str):
            initializer = _init.create(initializer)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name]._data.astype(arr.dtype))
            else:
                if arg_params is not None and not allow_missing:
                    raise MXNetError(f"parameter {name} missing from "
                                     "arg_params")
                initializer(_init.InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params and name in aux_params:
                arr._set_data(aux_params[name]._data.astype(arr.dtype))
            else:
                initializer(_init.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params = {n: self._exec.arg_dict[n].copy()
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copy()
                      for n in self._aux_names}
        return arg_params, aux_params

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # -- optimizer ------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        # kvstore: single-process aggregation is the identity here (one
        # executor); the API is kept so dist flows can swap in
        # mxtpu.kvstore backends
        self._kvstore = kvstore
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- computation ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if self._label_names and data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.optimizer_initialized
        # legacy Module API keeps the reference's per-param updater
        # semantics; new code should use gluon Trainer.make_fused_step
        for i, name in enumerate(self._param_names):  # mxlint: disable=MXL003
            grad = self._exec.grad_dict.get(name)
            if grad is None or self._exec.grad_req.get(name) == "null":
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            {name: lab for name, lab in zip(self._label_names, labels)},
            {name: out for name, out in
             zip(self.output_names, self._exec.outputs)})

    @property
    def output_names(self):
        return self.symbol.list_outputs()

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def data_shapes(self):
        return self._data_shapes

    # -- serialization --------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_params, aux_params = self.get_params()
        _save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod._preloaded_params = (args, auxs)
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
