"""BucketingModule: variable-length training via per-bucket executors
with shared parameters (reference
``python/mxnet/module/bucketing_module.py`` [path cite — unverified]).

One Module per bucket key; parameters copy-through on switch. On TPU
each bucket is its own compiled XLA program (shape-specialized), exactly
like the reference's per-bucket bound executors.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_config = None

    def _gen_module(self, bucket_key):
        if bucket_key in self._buckets:
            return self._buckets[bucket_key]
        sym, data_names, label_names = self._sym_gen(bucket_key)
        mod = Module(sym, data_names=data_names, label_names=label_names,
                     logger=self.logger, context=self._context,
                     **self._kwargs)
        self._buckets[bucket_key] = mod
        return mod

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training,
                 inputs_need_grad, force_rebind, grad_req)
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.symbol = mod.symbol
        self.binded = True

    def init_params(self, **kwargs):
        assert self.binded
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._opt_config = (kvstore, optimizer, optimizer_params)
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params, force_init)
        self.optimizer_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch the active bucket, sharing params from the current one
        (the reference's shared_module binding)."""
        assert self.binded
        prev = self._curr_module
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training,
                     inputs_need_grad=self._inputs_need_grad,
                     grad_req=self._grad_req)
            arg_params, aux_params = prev.get_params()
            mod.init_params(arg_params=arg_params, aux_params=aux_params,
                            allow_missing=False, force_init=True)
            if self._opt_config is not None:
                mod.init_optimizer(*self._opt_config)
                mod._updater = prev._updater    # shared optimizer state
        else:
            # refresh shared params from the previously-active bucket
            arg_params, aux_params = prev.get_params()
            mod.set_params(arg_params, aux_params)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key
        self.symbol = mod.symbol

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._curr_bucket_key
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._curr_module.set_params(arg_params, aux_params, **kwargs)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)
