"""mx.runtime — compiled-feature introspection (reference
``python/mxnet/runtime.py`` over ``src/libinfo.cc`` [path cites —
unverified]).

The reference reported build-time flags (USE_CUDA, USE_MKLDNN, ...);
here features reflect the live jax/XLA environment, probed once.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe() -> Dict[str, bool]:
    import jax
    platforms = set()
    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        pass
    try:
        import tensorflow  # noqa: F401
        has_tf_codec = True
    except Exception:
        has_tf_codec = False
    return {
        "TPU": "tpu" in platforms or any("tpu" in p or "axon" in p
                                         for p in platforms),
        "CPU": True,
        "CUDA": "gpu" in platforms or "cuda" in platforms,
        "CUDNN": False,
        "NCCL": False,
        "MKLDNN": False,
        "OPENMP": True,
        "BLAS_OPEN": True,
        "X64": bool(jax.config.jax_enable_x64),
        "DIST_KVSTORE": True,        # jax.distributed backend
        "INT64_TENSOR_SIZE": bool(jax.config.jax_enable_x64),
        "SIGNAL_HANDLER": True,
        "PROFILER": True,
        "TUTORIALS_EXIST": False,
        "OPENCV": False,
        "IMAGE_CODEC": has_tf_codec,
        "F16C": False,
        "JEMALLOC": False,
    }


class Features(dict):
    """Dict of Feature (reference ``mx.runtime.Features``)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            cls.instance.update(
                {k: Feature(k, v) for k, v in _probe().items()})
        return cls.instance

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"

    def is_enabled(self, name: str) -> bool:
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)


def feature_list():
    return list(Features().values())
