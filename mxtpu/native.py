"""ctypes bindings for libmxtpu (see ``src/libmxtpu.cc``) — the native
runtime components (RecordIO reader, JPEG decode, threaded decode
pipeline; the rebuild of the reference's C++ ``src/io`` stack).

The library builds lazily with g++ on first use (no pybind11 in the
environment — plain C ABI + ctypes per SURVEY.md environment notes);
everything degrades gracefully to the Python implementations when the
toolchain or libjpeg is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as onp

_LIB = None
_LOCK = threading.Lock()
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")


def _build() -> Optional[str]:
    so = os.path.join(_SRC_DIR, "libmxtpu.so")
    src = os.path.join(_SRC_DIR, "libmxtpu.cc")
    if os.path.exists(so):
        try:
            if os.path.getmtime(so) >= os.path.getmtime(src):
                return so
        except OSError:
            return so          # prebuilt .so shipped without source
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-shared",
             src, "-o", so, "-ljpeg", "-lpthread"],
            check=True, capture_output=True, timeout=120)
        return so
    except Exception:
        return None


def get_lib():
    """Load (building if needed) libmxtpu; None if unavailable."""
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB if _LIB is not False else None
        so = _build()
        if so is None:
            _LIB = False
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _LIB = False
            return None
        lib.mxtpu_rec_open.restype = ctypes.c_void_p
        lib.mxtpu_rec_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_rec_count.restype = ctypes.c_long
        lib.mxtpu_rec_count.argtypes = [ctypes.c_void_p]
        lib.mxtpu_rec_read.restype = ctypes.c_long
        lib.mxtpu_rec_read.argtypes = [
            ctypes.c_void_p, ctypes.c_long,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]
        lib.mxtpu_rec_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_jpeg_decode.restype = ctypes.c_long
        lib.mxtpu_jpeg_decode.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_ulong, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.mxtpu_pipe_create.restype = ctypes.c_void_p
        lib.mxtpu_pipe_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint, ctypes.c_int, ctypes.c_int]
        lib.mxtpu_pipe_next_u8.restype = ctypes.c_long
        lib.mxtpu_pipe_next_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_float)]
        lib.mxtpu_pipe_next.restype = ctypes.c_long
        lib.mxtpu_pipe_next.argtypes = [
            ctypes.c_void_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
        lib.mxtpu_pipe_reset.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


def available() -> bool:
    return get_lib() is not None


class NativeRecordReader:
    """Random-access RecordIO reader over the native offset index."""

    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._h = lib.mxtpu_rec_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __len__(self) -> int:
        return int(self._lib.mxtpu_rec_count(self._h))

    def read(self, i: int) -> bytes:
        ptr = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.mxtpu_rec_read(self._h, i, ctypes.byref(ptr))
        if n < 0:
            raise IndexError(i)
        return bytes(ctypes.cast(
            ptr, ctypes.POINTER(ctypes.c_ubyte * n)).contents)

    def close(self):
        if self._h:
            self._lib.mxtpu_rec_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def jpeg_decode(buf: bytes, channels: int = 3) -> onp.ndarray:
    """Native JPEG decode → HWC uint8."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("libmxtpu unavailable")
    arr = (ctypes.c_ubyte * len(buf)).from_buffer_copy(buf)
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    n = lib.mxtpu_jpeg_decode(arr, len(buf), channels, None,
                              ctypes.byref(w), ctypes.byref(h),
                              ctypes.byref(c))
    if n < 0:
        raise ValueError("JPEG decode failed")
    out = onp.empty(n, onp.uint8)
    lib.mxtpu_jpeg_decode(
        arr, len(buf), channels,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
    return out.reshape(h.value, w.value, c.value)


class NativePipeline:
    """Threaded read+decode+resize pipeline (the reference's C++
    ImageRecordIOParser2 + prefetcher, rebuilt)."""

    def __init__(self, rec_path: str, height: int, width: int,
                 channels: int = 3, shuffle: bool = False, seed: int = 0,
                 threads: int = 2, out_u8: bool = False):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._hwc = (height, width, channels)
        self._u8 = bool(out_u8)
        self._h = lib.mxtpu_pipe_create(rec_path.encode(), height, width,
                                        channels, int(shuffle), seed,
                                        threads, int(out_u8))
        if not self._h:
            raise IOError(f"cannot open {rec_path}")

    def next_batch(self, batch_size: int):
        """Returns (data (n,h,w,c), labels (n,)) with n ≤ batch_size;
        n==0 means the epoch is exhausted. Data is float32, or uint8
        when built with ``out_u8`` (quarter the host→device bytes —
        convert/normalize on the accelerator)."""
        h, w, c = self._hwc
        labels = onp.empty((batch_size,), onp.float32)
        lp = labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        if self._u8:
            data = onp.empty((batch_size, h, w, c), onp.uint8)
            n = self._lib.mxtpu_pipe_next_u8(
                self._h, batch_size,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), lp)
        else:
            data = onp.empty((batch_size, h, w, c), onp.float32)
            n = self._lib.mxtpu_pipe_next(
                self._h, batch_size,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), lp)
        if n < 0:
            raise RuntimeError("pipe output-mode mismatch (out_u8 flag "
                               "does not match the create() mode)")
        return data[:n], labels[:n]

    def reset(self):
        self._lib.mxtpu_pipe_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxtpu_pipe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
