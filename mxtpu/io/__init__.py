"""mx.io — the DataIter protocol and built-in iterators (reference
``python/mxnet/io/io.py`` + the C++ iterators ``src/io/`` [path cites —
unverified]).

The reference's C++ prefetching pipeline (dmlc::ThreadedIter) maps to
:class:`PrefetchingIter` — a background-thread double buffer; decode
runs in Python/TF, batching in numpy, and the final device_put overlaps
with TPU compute via PJRT async dispatch.
"""
from __future__ import annotations

import queue
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "MNISTIter",
           "LibSVMIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name + shape (+ dtype/layout) of one input (reference DataDesc)."""

    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """One minibatch: lists of data/label arrays + padding info."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data or []]
        lshapes = [l.shape for l in self.label or []]
        return f"DataBatch: data shapes: {shapes} label shapes: {lshapes}"


class DataIter:
    """Base iterator (reference ``mx.io.DataIter``)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _as_arrays(data, default_name: str):
    """Normalize array/list/dict input → list of (name, numpy array)."""
    if data is None:
        return []
    if isinstance(data, (NDArray, onp.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        out = []
        for i, d in enumerate(data):
            name = default_name if len(data) == 1 else \
                f"{default_name}_{i}"
            out.append((name, d.asnumpy() if isinstance(d, NDArray)
                        else onp.asarray(d)))
        return out
    if isinstance(data, dict):
        return [(k, v.asnumpy() if isinstance(v, NDArray)
                 else onp.asarray(v)) for k, v in sorted(data.items())]
    raise TypeError(f"cannot interpret {type(data)} as iterator data")


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference ``mx.io.NDArrayIter``):
    dict/list/array data+label, shuffle, last_batch_handle
    pad|discard|roll_over."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _as_arrays(data, data_name)
        self.label = _as_arrays(label, label_name)
        self.num_data = self.data[0][1].shape[0]
        if last_batch_handle == "discard":
            n = (self.num_data // batch_size) * batch_size
            self.data = [(k, v[:n]) for k, v in self.data]
            self.label = [(k, v[:n]) for k, v in self.label]
            self.num_data = n
        if self.num_data == 0:
            raise MXNetError("empty iterator")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = onp.arange(self.num_data)
        self.cursor = -batch_size
        self._rolled = None          # undelivered tail (roll_over mode)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        roll = self.last_batch_handle == "roll_over" and \
            0 < self.cursor < self.num_data
        if roll:
            # capture the undelivered tail BEFORE reshuffling, so the
            # rolled batch serves exactly the held-over samples
            self._rolled = self.idx[self.cursor:].copy()
        if self.shuffle:
            onp.random.shuffle(self.idx)
        if roll:
            # tail of this epoch rolls into the next epoch's first batch
            # (cursor goes negative; _take pulls from _rolled + new head)
            self.cursor = -len(self._rolled) - self.batch_size
        else:
            self._rolled = None
            self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        if self.last_batch_handle == "roll_over":
            # a rolled batch (negative cursor) is full; otherwise only
            # whole batches are served — the tail waits for the next epoch
            return self.cursor < 0 or \
                self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _batch_indices(self):
        """Index array for the current batch — the single source for
        both the data served (_take) and the reported order (getindex)."""
        lo = self.cursor
        hi = self.cursor + self.batch_size
        if lo < 0:       # roll_over: previous epoch's tail + new head
            return onp.concatenate([self._rolled, self.idx[:hi]]) \
                if hi > 0 else self._rolled
        if hi <= self.num_data:
            return self.idx[lo:hi]
        # pad: wrap around from the head
        return onp.concatenate(
            [self.idx[lo:], self.idx[:hi - self.num_data]])

    def _take(self, arrays):
        sel = self._batch_indices()
        return [nd.array(v[sel], dtype=v.dtype) for _, v in arrays]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self) -> int:
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        return self._batch_indices()


class CSVIter(DataIter):
    """CSV reader (reference ``src/io/iter_csv.cc``): ``data_csv`` +
    optional ``label_csv``, fixed row shapes."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32,
                           ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = onp.loadtxt(label_csv, delimiter=",",
                                dtype=onp.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if tuple(label_shape) == (1,):
                label = label.reshape(-1)
        else:
            label = onp.zeros((data.shape[0],), onp.float32)
        self._it = NDArrayIter(
            {data_name: data}, {label_name: label}, batch_size,
            last_batch_handle="pad" if round_batch else "discard")
        self.provide_data = self._it.provide_data
        self.provide_label = self._it.provide_label

    def reset(self):
        self._it.reset()

    def next(self):
        return self._it.next()


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches
    (reference ``mx.io.ResizeIter``)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference ``mx.io.PrefetchingIter`` /
    dmlc::ThreadedIter): decodes batch k+1 while the TPU runs batch k."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch: int = 2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            # reference supports zipping several iters; single covers the
            # training use; keep the API
            raise NotImplementedError(
                "PrefetchingIter currently wraps one iterator")
        self._it = iters[0]
        super().__init__(self._it.batch_size)
        self.provide_data = self._it.provide_data
        self.provide_label = self._it.provide_label
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._thread = None
        self._stop = threading.Event()
        self._done = False
        self._start()

    def _start(self):
        self._stop.clear()

        def worker():
            while not self._stop.is_set():
                try:
                    item = self._it.next()
                except StopIteration:
                    item = None
                except Exception as e:        # surface errors to consumer
                    item = e
                # abortable put: reset()/close() must be able to join
                # even when the consumer stopped draining — a worker
                # parked forever in Queue.put would be killed mid-
                # decode at interpreter exit (native-thread terminate)
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if item is None or isinstance(item, Exception):
                    return

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _halt(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            # wait for the CURRENT inner batch to finish: the worker
            # re-checks _stop between batches, so this is bounded by
            # one batch's decode time. A short timeout here left a
            # daemon thread to be killed inside native decode at
            # interpreter exit ("FATAL: exception not rethrown").
            self._thread.join(timeout=300)
            if self._thread.is_alive():
                raise RuntimeError(
                    "prefetch worker failed to stop (inner iterator "
                    "hung?) — not restarting over a live worker")
            self._thread = None

    def reset(self):
        self._halt()
        self._it.reset()
        self._queue = queue.Queue(maxsize=self._queue.maxsize)
        self._done = False
        self._start()

    def close(self):
        """Stop the prefetch thread deterministically (join, not
        daemon-kill at exit) and close the inner iterator."""
        self._halt()
        self._done = True      # next() must raise, not block forever
        if hasattr(self._it, "close"):
            self._it.close()

    def next(self):
        if self._done:
            # keep raising after exhaustion (DataIter contract) instead
            # of blocking on a queue with no producer
            raise StopIteration
        item = self._queue.get()
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        return item

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=1,
                    shuffle=False, preprocess_threads=2, prefetch_buffer=2,
                    **kwargs) -> DataIter:
    """RecordIO image pipeline (reference C++ ``ImageRecordIter``,
    ``src/io/iter_image_recordio_2.cc``).

    Accepts the reference's flag names (mean_r/g/b, std_r/g/b,
    rand_mirror, rand_crop, ...). When only decode/resize/normalize are
    requested and libmxtpu built, the C++ threaded pipeline serves the
    batches (orders of magnitude faster than the TF-decode path);
    augmentation flags route through the Python ImageIter."""
    mean = None
    if any(f"mean_{c}" in kwargs for c in "rgb"):
        mean = [kwargs.pop("mean_r", 0.0), kwargs.pop("mean_g", 0.0),
                kwargs.pop("mean_b", 0.0)]
    std = None
    if any(f"std_{c}" in kwargs for c in "rgb"):
        std = [kwargs.pop("std_r", 1.0), kwargs.pop("std_g", 1.0),
               kwargs.pop("std_b", 1.0)]
    # route natively only when EVERY remaining kwarg is semantics the
    # C++ pipeline implements (decode + center-crop + resize + mean/std);
    # anything else (label_width, hue, inter_method, augmenters, ...)
    # goes through the Python ImageIter
    native_ok_keys = {"seed", "data_name", "label_name"}
    device_pipeline = kwargs.pop("device_pipeline", True)
    blocking = {k for k, v in kwargs.items()
                if k not in native_ok_keys and v}
    if not blocking and data_shape and data_shape[0] == 3:
        from .. import native
        if native.available():
            return NativeImageRecordIter(
                path_imgrec=path_imgrec, data_shape=data_shape,
                batch_size=batch_size, shuffle=shuffle,
                preprocess_threads=preprocess_threads, mean=mean, std=std,
                seed=int(kwargs.get("seed", 0)),
                device_pipeline=device_pipeline)
    from ..image import ImageIter
    inner = ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                      shuffle=shuffle, mean=mean, std=std, **kwargs)
    return PrefetchingIter(inner, prefetch=prefetch_buffer)


def MNISTIter(image=None, label=None, batch_size=1, shuffle=False,
              flat=False, **kwargs) -> DataIter:
    """MNIST idx-format reader (reference ``src/io/iter_mnist.cc``)."""
    import gzip
    import struct as _struct

    def read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = _struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = _struct.unpack(f">{ndim}I", f.read(4 * ndim))
            return onp.frombuffer(f.read(), onp.uint8).reshape(dims)

    images = read_idx(image).astype(onp.float32) / 255.0
    labels = read_idx(label).astype(onp.float32)
    images = images.reshape(len(images), -1) if flat else \
        images[:, None, :, :]
    return NDArrayIter(images, labels, batch_size, shuffle=shuffle)


class LibSVMIter(DataIter):
    """LibSVM sparse text reader (reference ``src/io/iter_libsvm.cc``) —
    materializes dense batches; the sparse path lives in mxtpu.sparse."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_shape=None, round_batch=True, **kwargs):
        super().__init__(batch_size)
        num_features = int(onp.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = onp.zeros(num_features, onp.float32)
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = onp.stack(rows).reshape((-1,) + tuple(data_shape))
        self._it = NDArrayIter(
            data, onp.asarray(labels, onp.float32), batch_size,
            last_batch_handle="pad" if round_batch else "discard")
        self.provide_data = self._it.provide_data
        self.provide_label = self._it.provide_label

    def reset(self):
        self._it.reset()

    def next(self):
        return self._it.next()


class NativeImageRecordIter(DataIter):
    """C++ decode pipeline (libmxtpu): threaded RecordIO read + libjpeg
    decode + bilinear resize off the Python thread — the native
    counterpart of ImageRecordIter (reference C++ iterator parity).

    The hot path is split TPU-first: the HOST does only the irregular
    work (read, JPEG decode, crop/resize) and hands over rounded uint8
    HWC — a quarter of the float bytes — while convert-to-f32,
    mean/std normalization, and the HWC→CHW layout change run ON
    DEVICE as one cached jitted program (async; overlaps the next
    batch's decode). Measured on the 1-core dev box this takes the
    iterator from 189 → ~500 img/s at 224px (benchmark/input_bench.py).
    ``device_pipeline=False`` restores the all-host float32 path (the
    C++ pipeline emits f32 and numpy normalizes/transposes) for
    consumers that must not touch the accelerator."""

    def __init__(self, path_imgrec, data_shape, batch_size=1,
                 shuffle=False, seed=0, preprocess_threads=2,
                 mean=None, std=None, data_name="data",
                 label_name="softmax_label", device_pipeline=True,
                 **kwargs):
        from ..native import NativePipeline
        super().__init__(batch_size)
        c, h, w = data_shape
        self._device = bool(device_pipeline)
        self._pipe = NativePipeline(path_imgrec, h, w, c, shuffle, seed,
                                    preprocess_threads,
                                    out_u8=self._device)
        self._shape = (c, h, w)
        self._mean = onp.asarray(mean, onp.float32) if mean is not None \
            else None
        self._std = onp.asarray(std, onp.float32) if std is not None \
            else None
        self._post = None
        self.provide_data = [DataDesc(data_name, (batch_size,) + self._shape)]
        self.provide_label = [DataDesc(label_name, (batch_size,))]

    def reset(self):
        self._pipe.reset()

    def _device_post(self):
        """One jitted u8-HWC → normalized-f32-CHW program (built once
        per iterator; mean/std baked as constants so XLA folds them
        into the convert)."""
        if self._post is None:
            import jax
            import jax.numpy as jnp
            mean, std = self._mean, self._std

            def post(x):
                y = x.astype(jnp.float32)
                if mean is not None:
                    y = y - mean
                if std is not None:
                    y = y / std
                return y.transpose(0, 3, 1, 2)

            self._post = jax.jit(post)
        return self._post

    def next(self):
        data, labels = self._pipe.next_batch(self.batch_size)
        if len(data) == 0:
            raise StopIteration
        pad = self.batch_size - len(data)
        if pad:
            data = onp.concatenate(
                [data, onp.zeros((pad,) + data.shape[1:], data.dtype)])
            labels = onp.concatenate([labels, onp.zeros(pad, onp.float32)])
        if self._device:
            out = nd.NDArray(self._device_post()(data))
            return DataBatch(data=[out], label=[nd.array(labels)],
                             pad=pad)
        if self._mean is not None:
            data = data - self._mean
        if self._std is not None:
            data = data / self._std
        # HWC → CHW (contiguous BEFORE device_put: jax copies strided
        # inputs element-wise, ~3× the cost of ascontiguousarray+put)
        data = onp.ascontiguousarray(data.transpose(0, 3, 1, 2))
        return DataBatch(data=[nd.array(data)], label=[nd.array(labels)],
                         pad=pad)

    def close(self):
        self._pipe.close()
