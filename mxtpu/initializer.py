"""Weight initializers (reference ``python/mxnet/initializer.py`` [path cite]).

Same registry + descriptor design as the reference: an ``Initializer``
dispatches on the parameter name's suffix (``_weight``/``_bias``/``_gamma``/
``_beta``/``_mean``/``_var``) unless an ``InitDesc`` attr overrides, and
string names like ``"xavier"`` resolve through a registry
(``mx.init.registry`` analogue). Sampling goes through ``mxtpu.nd.random``
so seeding is controlled by ``mx.random.seed``.
"""
from __future__ import annotations

import json
import math
from typing import Any, Dict, Optional

import numpy as _np

from . import ndarray as nd
from .ndarray import random as _random

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "register", "create"]

_INIT_REGISTRY: Dict[str, type] = {}


def register(klass):
    """Register an initializer class under its lowercased name."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init: Any, **kwargs) -> "Initializer":
    """Resolve ``init`` (Initializer | str | None) to an Initializer."""
    if init is None:
        return Uniform()
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _INIT_REGISTRY:
            raise ValueError(f"unknown initializer {init!r}; "
                             f"registered: {sorted(_INIT_REGISTRY)}")
        return _INIT_REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create initializer from {init!r}")


class InitDesc(str):
    """Parameter name + attrs describing how to initialize it
    (reference ``mx.init.InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer: ``init(desc, arr)`` fills ``arr`` in place."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr) -> None:
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            create(json.loads(init)[0], **json.loads(init)[1]) \
                ._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean") \
                or name.endswith("mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var") \
                or name.endswith("var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- suffix rules (reference behavior) ----------------------------------
    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


_INIT_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        val = self.value
        if hasattr(val, "asnumpy"):
            val = val.asnumpy()
        arr[:] = val


@register
class Uniform(Initializer):
    """U(-scale, scale) — the reference's default global init (scale 0.07)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        _random.uniform(-self.scale, self.scale, arr.shape,
                        dtype=arr.dtype, out=arr)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        _random.normal(0.0, self.sigma, arr.shape, dtype=arr.dtype, out=arr)


@register
class Xavier(Initializer):
    """Glorot init (reference ``mx.init.Xavier``)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        if len(shape) < 2:
            raise ValueError(
                f"Xavier requires at least 2D weight, got {shape} for {name}")
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = float(_np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            _random.uniform(-scale, scale, shape, dtype=arr.dtype, out=arr)
        elif self.rnd_type == "gaussian":
            _random.normal(0.0, scale, shape, dtype=arr.dtype, out=arr)
        else:
            raise ValueError(f"unknown rnd_type {self.rnd_type}")


@register
class MSRAPrelu(Xavier):
    """Kaiming init (reference ``mx.init.MSRAPrelu``)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr[:] = self.scale * q.reshape(arr.shape)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference ``mx.init.LSTMBias``)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        a = arr.asnumpy()
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = a

    _init_bias = _init_weight


class Mixed:
    """Per-pattern initializer mix (reference ``mx.init.Mixed``)."""

    def __init__(self, patterns, initializers):
        import re
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")
