"""mxtpu.ops — TPU-native fused kernels (Pallas + structured lax).

The reference's hand-written CUDA/cuDNN kernels (``src/operator/nn/``,
``src/operator/contrib/transformer.cc`` [path cite]) map here: most ops
are jnp/lax compositions that XLA fuses; this package holds the ones
that need explicit structure — attention (flash/ring), and future
sharded-embedding / fused-optimizer kernels.
"""
from .attention import (blockwise_attention, dense_attention,
                        flash_attention, ring_attention)

__all__ = ["blockwise_attention", "dense_attention", "flash_attention",
           "ring_attention"]
