"""Attention kernels: dense, blockwise (flash-style online softmax),
Pallas flash on TPU, and ring attention over the ``sp`` mesh axis.

NEW components with no reference counterpart (SURVEY.md §5.7: MXNet
predates sequence parallelism; nearest in-tree artifact is the
interleaved MHA contrib op, ``src/operator/contrib/transformer.cc``
[path cite]). Design per the ring-attention recipe: blockwise attention
with running (max, denom, numerator) statistics; the ring variant
rotates KV shards around the sequence axis with ``lax.ppermute`` inside
``shard_map``, overlapping compute with ICI transfers.

All functions take (batch, num_heads, seq, head_dim) arrays. GQA is
supported: kv arrays may have fewer heads (num_heads % kv_heads == 0).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["dense_attention", "blockwise_attention", "flash_attention",
           "ulysses_attention",
           "ring_attention", "slot_decode_attention",
           "paged_decode_attention"]

_NEG_INF = -1e30  # finite "minus infinity": keeps fully-masked rows NaN-free


def _repeat_kv(q, k, v):
    """Broadcast grouped KV heads up to the query head count (GQA)."""
    hq, hk = q.shape[1], k.shape[1]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return k, v


def dense_attention(q, k, v, *, causal: bool = False,
                    mask: Optional[jax.Array] = None,
                    scale: Optional[float] = None,
                    q_offset: int = 0, kv_offset: int = 0):
    """Reference-semantics attention, fully materialized scores.

    ``q_offset``/``kv_offset`` are the global positions of element 0 —
    used by the ring variant where each device holds a sequence shard.
    """
    k, v = _repeat_kv(q, k, v)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    allowed = None
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2]) + kv_offset
        allowed = (qpos[:, None] >= kpos[None, :])[None, None]
    if mask is not None:
        allowed = mask if allowed is None else (allowed & mask)
    if allowed is None:
        probs = jax.nn.softmax(scores, axis=-1)
    else:
        # masked softmax with fully-masked rows → zeros (matches the
        # blockwise/ring _finalize semantics), not uniform attention
        scores = jnp.where(allowed, scores, _NEG_INF)
        e = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
        e = jnp.where(allowed, e, 0.0)
        denom = e.sum(axis=-1, keepdims=True)
        probs = e / jnp.where(denom == 0.0, 1.0, denom)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _online_block(q, k, v, m, l, o, scale, causal, q_off, kv_off,
                  extra_mask=None):
    """One flash step: fold a KV block into running (m, l, o) stats.

    m: (b,h,q) running row max; l: (b,h,q) running denominator;
    o: (b,h,q,d) running unnormalized numerator. All float32.
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    allowed = None
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_off
        kpos = jnp.arange(k.shape[2]) + kv_off
        allowed = (qpos[:, None] >= kpos[None, :])[None, None]
    if extra_mask is not None:
        allowed = extra_mask if allowed is None else (allowed & extra_mask)
    if allowed is not None:
        scores = jnp.where(allowed, scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if allowed is not None:
        # fully-masked rows keep m_new == _NEG_INF, where exp(score -
        # m_new) == 1 would silently attend uniformly — zero them so l
        # stays 0 and _finalize emits zeros for such rows
        p = jnp.where(allowed, p, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def _finalize(m, l, o, dtype):
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
    return (o / l[..., None]).astype(dtype)


def blockwise_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None,
                        kv_block: int = 512,
                        q_offset: int = 0, kv_offset: int = 0):
    """Flash-style attention as a ``lax.scan`` over KV blocks: O(seq)
    memory, MXU-friendly block matmuls, no materialized score matrix."""
    k, v = _repeat_kv(q, k, v)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = q.shape
    skv = k.shape[2]
    kv_block = min(kv_block, skv)
    nblk, rem = divmod(skv, kv_block)
    if rem:  # pad KV to a block multiple; padded keys are masked by offset
        pad = kv_block - rem
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        nblk += 1
    else:
        pad = 0

    kb = k.reshape(b, h, nblk, kv_block, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, kv_block, d).transpose(2, 0, 1, 3, 4)

    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)

    def body(carry, xs):
        m, l, o = carry
        i, kblk, vblk = xs
        blk_off = kv_offset + i * kv_block
        # padded tail keys: positions >= kv_offset+skv are masked out
        kpos = jnp.arange(kv_block) + blk_off
        valid = kpos < kv_offset + skv
        m2, l2, o2 = _online_block(
            q, kblk, vblk, m, l, o, scale, causal, q_offset,
            blk_off, extra_mask=valid[None, None, None, :])
        return (m2, l2, o2), None

    (m, l, o), _ = lax.scan(body, (m0, l0, o0),
                            (jnp.arange(nblk), kb, vb))
    return _finalize(m, l, o, q.dtype)


def _tpu_pallas_flash(q, k, v, causal, scale):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _pl_flash, BlockSizes)
    # measured v5e sweep at (b4, h16, s2048, d128), fwd+bwd: the
    # kernel's defaults run 24.4 ms; bq=1024/bk=512 runs 9.8 ms (dense
    # is 15.5). Q-blocks want to be wide (amortize the KV stream);
    # K-blocks at 512 keep the VMEM working set resident.
    sq, skv = q.shape[2], k.shape[2]
    bq = next(c for c in (1024, 512, 256, 128) if sq % c == 0)
    bk = next(c for c in (512, 256, 128) if skv % c == 0)
    bs = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq)
    return _pl_flash(q, k, v, causal=causal, sm_scale=scale,
                     block_sizes=bs)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    kv_block: int = 512):
    """Fused attention: Pallas (Mosaic) kernel on TPU, blockwise scan
    elsewhere. This is the rebuild's hot-path attention op — the role
    cuDNN's fused MHA played in the reference."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kr, vr = _repeat_kv(q, k, v)
    if q.ndim == 4 and jax.default_backend() == "tpu":
        # Mosaic wants block-aligned seq lens; fall back otherwise.
        sq, skv, d = q.shape[2], kr.shape[2], q.shape[3]
        if sq % 128 == 0 and skv % 128 == 0 and d % 128 == 0:
            try:
                return _tpu_pallas_flash(q, kr, vr, causal, scale)
            except Exception:
                pass
    return blockwise_attention(q, kr, vr, causal=causal, scale=scale,
                               kv_block=kv_block)


def slot_decode_attention(q, k, v, lengths, *, scale: Optional[float] = None,
                          kv_block: int = 512):
    """Length-masked decode attention over a SLOT KV cache — the
    serving engine's kernel (``mxtpu.serve``): each slot holds an
    independent request whose cache row is valid only up to its own
    ``lengths[i]``, so one fixed-shape program serves a ragged batch.

    q: (slots, n_heads, s, hd) — the new token(s), s is 1 in decode.
    k, v: (slots, n_kv_heads, max_len, hd) — the per-layer slot cache
    (GQA: ``n_heads % n_kv_heads == 0``; queries are grouped per kv
    head, the cache is never repeated).
    lengths: (slots,) int — slot i attends keys ``[0, lengths[i])`` —
    or (slots, s) int for PER-QUERY lengths: query j of slot i attends
    ``[0, lengths[i, j])``. The 2-D form is the speculative verify
    step's causal mask (query j sees the prefix plus the j drafted
    tokens before it) and reduces to the 1-D form at s == 1, so the
    decode fast path is unchanged.

    Blockwise flash-style online softmax over ``kv_block``-wide KV
    slices: the (s, max_len) score matrix is never materialized — only
    one (slots, groups, rep, s, kv_block) block of scores lives at a
    time, with running (max, denom, numerator) carries. Fully-masked
    rows (lengths == 0) come out as zeros, matching ``dense_attention``
    masked-softmax semantics."""
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    if hq % hkv:
        raise ValueError(f"{hq} q heads not divisible by {hkv} kv heads")
    rep = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len = k.shape[2]
    lengths = lengths.astype(jnp.int32)
    kv_block = min(kv_block, max_len)
    nblk, remv = divmod(max_len, kv_block)
    if remv:  # pad the cache tail; padded keys are masked by position
        pad = kv_block - remv
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        nblk += 1

    qg = q.reshape(b, hkv, rep, sq, d)
    kb = k.reshape(b, hkv, nblk, kv_block, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, hkv, nblk, kv_block, d).transpose(2, 0, 1, 3, 4)

    m0 = jnp.full((b, hkv, rep, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, rep, sq), jnp.float32)
    o0 = jnp.zeros((b, hkv, rep, sq, d), jnp.float32)

    def body(carry, xs):
        m, l, o = carry
        i, kblk, vblk = xs
        scores = jnp.einsum("bgrsd,bgkd->bgrsk", qg, kblk,
                            preferred_element_type=jnp.float32) * scale
        kpos = i * kv_block + jnp.arange(kv_block)       # (kv_block,)
        if lengths.ndim == 2:   # per-query: (b, sq, kv_block)
            allowed = kpos[None, None, :] < lengths[:, :, None]
            allowed = allowed[:, None, None, :, :]
        else:
            allowed = kpos[None, :] < lengths[:, None]   # (b, kv_block)
            allowed = allowed[:, None, None, None, :]
        scores = jnp.where(allowed, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(allowed, p, 0.0)   # length-0 slots stay all-zero
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bgrsk,bgkd->bgrsd", p, vblk.astype(jnp.float32))
        return (m_new, l_new, o_new), None

    (m, l, o), _ = lax.scan(body, (m0, l0, o0),
                            (jnp.arange(nblk), kb, vb))
    out = _finalize(m, l, o, q.dtype)
    return out.reshape(b, hq, sq, d)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           scale: Optional[float] = None,
                           kv_block: int = 512):
    """Decode attention over a PAGED KV pool (vLLM's PagedAttention,
    Kwon et al. SOSP '23): the cache is a flat pool of fixed-size pages
    and each slot's logical KV sequence is the concatenation of the
    pool pages its row of ``page_table`` names. Gather + the blockwise
    ``slot_decode_attention`` online softmax — bit-exact with the dense
    slot kernel on the same logical KV (the gather materializes the
    identical (slots, kvh, capacity, hd) operand; trailing pages past
    ``lengths`` are fully masked, which the online-softmax scan treats
    as an exact no-op: m unchanged, corr = exp(0) = 1, p zeroed).

    q: (slots, n_heads, s, hd) — s is 1 in decode.
    k_pages, v_pages: (n_pages, n_kv_heads, page_size, hd) — the shared
    pool. Page 0 is the engine's scratch page (never attended: every
    real table entry covering positions < lengths names a live page).
    page_table: (slots, pages_per_slot) int32 — slot i's logical page j
    lives at pool index ``page_table[i, j]``.
    lengths: (slots,) int — slot i attends positions ``[0, lengths[i])``
    of its gathered sequence — or (slots, s) for per-query lengths,
    passed straight through to the slot kernel (the speculative
    verify step's mask).
    """
    if q.shape[0] != page_table.shape[0]:
        raise ValueError(
            f"page_table rows {page_table.shape[0]} != slots {q.shape[0]}")
    n_pages, hkv, page_size, d = k_pages.shape
    slots, per_slot = page_table.shape
    # gather (S, P, kvh, ps, hd) → contiguous (S, kvh, P*ps, hd)
    def flat(pool):
        g = jnp.take(pool, page_table, axis=0)
        return (g.transpose(0, 2, 1, 3, 4)
                 .reshape(slots, hkv, per_slot * page_size, d))
    return slot_decode_attention(q, flat(k_pages), flat(v_pages), lengths,
                                 scale=scale, kv_block=kv_block)


def ring_attention(q, k, v, *, axis_name: str = "sp",
                   causal: bool = False,
                   scale: Optional[float] = None,
                   kv_block: int = 512):
    """Ring attention over the ``axis_name`` mesh axis.

    Call INSIDE ``shard_map`` where q/k/v hold this device's sequence
    shard. Each of the ``n`` ring steps computes blockwise attention of
    the local Q against the currently-held KV shard, then rotates KV to
    the next device with ``ppermute`` — total memory O(seq/n), ICI
    traffic fully overlapped by XLA's async collective scheduling.
    """
    k, v = _repeat_kv(q, k, v)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    skv = k.shape[2]

    # derive the running stats from q so they inherit q's varying-
    # manual-axes set (jax>=0.8 types carries by vma; fresh zeros would
    # be unvarying and fail the fori_loop carry check)
    zero = (q[..., 0] * 0).astype(jnp.float32)
    m0 = zero + _NEG_INF
    l0 = zero
    o0 = (q * 0).astype(jnp.float32)

    from ..parallel.collectives import ppermute_ring

    def body(i, carry):
        m, l, o, kc, vc = carry
        # after i rotations (shift=+1) this device holds the shard that
        # started on device (my - i) mod n
        kv_idx = (my - i) % n
        q_off = my * sq
        kv_off = kv_idx * skv
        m, l, o = _online_block(q, kc, vc, m, l, o, scale, causal,
                                q_off, kv_off)
        kc = ppermute_ring(kc, axis_name)
        vc = ppermute_ring(vc, axis_name)
        return m, l, o, kc, vc

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    return _finalize(m, l, o, q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      kv_block: int = 512):
    """Ulysses-style sequence parallelism (DeepSpeed-Ulysses; SURVEY
    §5.7(c)): two all-to-alls reshard sequence-sharded QKV into
    head-sharded full-sequence tensors, attention runs locally over the
    FULL sequence for this device's head subset, and a final all-to-all
    restores the sequence sharding.

    Call INSIDE ``shard_map`` with q/k/v holding this device's sequence
    shard, shapes (b, h, s/n, d). Heads must divide by the axis size.
    vs ring attention: 4 all-to-alls (q, k, v, out) instead of n KV
    rotations — wins when heads ≥ devices and seq is very long. KV
    cross the wire UN-repeated (GQA head count) whenever the kv-head
    count divides the axis, so grouped-query models pay kv-sized, not
    q-sized, K/V collectives.
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    h = q.shape[1]
    h_kv = k.shape[1]
    if h % n:
        raise ValueError(f"{h} heads not divisible over {n} '"
                         f"{axis_name}' devices (Ulysses reshard)")

    def seq_to_heads(x):
        # (b, h, s/n, d) → (b, h/n, s, d)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    if h_kv % n == 0:
        # reshard the GQA-sized KV, repeat locally AFTER the collective
        qh = seq_to_heads(q)
        kh = seq_to_heads(k)
        vh = seq_to_heads(v)
        kh, vh = _repeat_kv(qh, kh, vh)
    else:
        k, v = _repeat_kv(q, k, v)
        qh = seq_to_heads(q)
        kh = seq_to_heads(k)
        vh = seq_to_heads(v)
    # full sequence present locally → plain causal masking works; use
    # the blockwise kernel (O(seq) memory) over the local head subset
    oh = blockwise_attention(qh, kh, vh, causal=causal, scale=scale,
                             kv_block=kv_block)
    return heads_to_seq(oh)
