"""mx.engine (reference ``python/mxnet/engine.py``): execution-engine
knobs. The ThreadedEngine's bulking (batching op pushes into one engine
segment) maps to XLA fusion under jit — the bulk-size knobs are accepted
and recorded for API parity; the NaiveEngine debug mode (sync after
every op) is honored via MXNET_ENGINE_TYPE, as in the reference."""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = 15


def set_bulk_size(size: int) -> int:
    """Set the engine bulk size; returns the previous value (reference
    ``mx.engine.set_bulk_size``)."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Scope with a given bulk size (reference ``mx.engine.bulk``)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
