"""mx.engine (reference ``python/mxnet/engine.py``): execution-engine
knobs. The ThreadedEngine's bulking (batching op pushes into one engine
segment) maps to XLA fusion under jit — the bulk-size knobs are accepted
and recorded for API parity; the NaiveEngine debug mode (sync after
every op) is honored via MXNET_ENGINE_TYPE, as in the reference.

The numeric sanitizer (SURVEY §5.2) goes further than NaiveEngine:
``set_debug_nans(True)`` / ``MXTPU_DEBUG_NANS=1`` checks every jitted
program's outputs for NaN and re-runs op-by-op to NAME the producing
primitive — the role the reference's per-op asnumpy() debugging played,
but working inside fused programs."""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size", "set_debug_nans", "debug_nans"]


def set_debug_nans(enabled: bool) -> bool:
    """Toggle the NaN sanitizer at runtime; returns the previous
    setting. On a NaN inside any jitted program, raises
    FloatingPointError naming the producing primitive."""
    import jax
    prev = bool(jax.config.jax_debug_nans)
    jax.config.update("jax_debug_nans", bool(enabled))
    return prev


@contextlib.contextmanager
def debug_nans(enabled: bool = True):
    """Scope with the NaN sanitizer on (or off)."""
    prev = set_debug_nans(enabled)
    try:
        yield
    finally:
        set_debug_nans(prev)

_BULK_SIZE = 15


def set_bulk_size(size: int) -> int:
    """Set the engine bulk size; returns the previous value (reference
    ``mx.engine.set_bulk_size``)."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Scope with a given bulk size (reference ``mx.engine.bulk``)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
