"""Evaluation metrics (reference ``python/mxnet/metric.py`` [path cite]).

Pure Python over the array API, ported 1:1 in behavior: ``update(labels,
preds)`` accumulates, ``get()`` returns (name, value). The only TPU-aware
change: accumulation happens in NumPy on host after an explicit sync —
metrics are the one place the reference docs allow a sync per batch.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as _np

from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "CustomMetric",
           "create", "np"]

_METRIC_REGISTRY: Dict[str, type] = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs) -> "EvalMetric":
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "nll_loss": "negativeloglikelihood",
               "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy",
               "pearsonr": "pearsoncorrelation"}
    name = aliases.get(name, name)
    if name not in _METRIC_REGISTRY:
        raise ValueError(f"unknown metric {metric!r}")
    return _METRIC_REGISTRY[name](*args, **kwargs)


def _as_numpy(x) -> _np.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def _listify(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class EvalMetric:
    def __init__(self, name: str, output_names=None, label_names=None,
                 **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get_name_value()))}"

    def reset(self) -> None:
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds) -> None:
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label: Dict, pred: Dict) -> None:
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric) -> None:
        self.metrics.append(create(metric))

    def get_metric(self, index: int):
        return self.metrics[index]

    def reset(self) -> None:
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds) -> None:
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.extend(_listify(name))
            values.extend(_listify(value))
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype("int32").reshape(-1)
            topk = _np.argsort(pred, axis=-1)[:, -self.top_k:]
            for j in range(self.top_k):
                self.sum_metric += float((topk[:, j] == label).sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    """Binary F1 (reference behavior: preds are class-1 probabilities or
    2-col scores; average='macro'|'micro')."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._tp = self._fp = self._fn = 0.0
        self._scores: List[float] = []
        super().__init__(name, output_names, label_names)

    def reset(self) -> None:
        super().reset()
        self._tp = self._fp = self._fn = 0.0
        self._scores = []

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype("int32").reshape(-1)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred_cls = pred.argmax(axis=-1).reshape(-1)
            else:
                pred_cls = (pred.reshape(-1) > 0.5).astype("int32")
            tp = float(((pred_cls == 1) & (label == 1)).sum())
            fp = float(((pred_cls == 1) & (label == 0)).sum())
            fn = float(((pred_cls == 0) & (label == 1)).sum())
            if self.average == "macro":
                prec = tp / (tp + fp) if tp + fp else 0.0
                rec = tp / (tp + fn) if tp + fn else 0.0
                f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
                self._scores.append(f1)
            else:
                self._tp += tp
                self._fp += fp
                self._fn += fn
            self.num_inst += 1

    def get(self):
        if self.average == "macro":
            if not self._scores:
                return self.name, float("nan")
            return self.name, sum(self._scores) / len(self._scores)
        prec = self._tp / (self._tp + self._fp) if self._tp + self._fp else 0.0
        rec = self._tp / (self._tp + self._fn) if self._tp + self._fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return self.name, f1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.shape != pred.shape:
                label = label.reshape(pred.shape)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.shape != pred.shape:
                label = label.reshape(pred.shape)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.sqrt(self.sum_metric / self.num_inst)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_numpy(label).ravel().astype("int64")
            pred = _as_numpy(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds) -> None:
        loss = 0.0
        num = 0
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_numpy(label).astype("int64")
            pred = _as_numpy(pred)
            flat_label = label.ravel()
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_np.arange(flat_label.shape[0]), flat_label]
            if self.ignore_label is not None:
                ignore = (flat_label == self.ignore_label)
                prob = prob[~ignore]
            loss += float(-_np.log(_np.maximum(prob, 1e-10)).sum())
            num += prob.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self.sum_metric += float(_np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of raw loss values (reference ``mx.metric.Loss``)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds) -> None:
        for pred in _listify(preds):
            loss = _as_numpy(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds) -> None:
        for label, pred in zip(_listify(labels), _listify(preds)):
            reval = self._feval(_as_numpy(label), _as_numpy(pred))
            if isinstance(reval, tuple):
                num, value = reval
                self.sum_metric += value
                self.num_inst += num
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference ``mx.metric.np``)."""
    return CustomMetric(numpy_feval, name, allow_extra_outputs)
