"""mxlint graph-validity pass (rule ``MXL100``) — static shape/dtype
checking over a traced ``Symbol`` program.

A thin reporting layer over ``Symbol._infer_structs_impl`` — the SAME
walker the real inference/bind/export paths run (one implementation,
so the diagnostic cannot drift from actual inference). The first
inconsistent node is reported with its op name, node name, and the
inferred input shapes — a real diagnostic instead of a deep error
three frames into a converter. No kernels run; abstract evaluation
only.

Used three ways:
- ``Symbol.validate(**shapes)`` — user-facing pre-flight check;
- the ONNX exporter (``mxtpu.contrib.onnx``) — a graph that fails
  validation aborts export with the formatted diagnostic;
- ``tests/test_mxlint.py`` — the tier-1 gate seeds a malformed graph
  and asserts the diagnostic names the op and shapes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["GraphIssue", "validate_graph", "format_issues"]


@dataclass
class GraphIssue:
    """One graph-validity violation (rule MXL100)."""
    op: str
    name: str
    message: str
    input_shapes: List[Optional[Tuple[int, ...]]] = field(
        default_factory=list)
    rule: str = "MXL100"

    def __str__(self) -> str:
        shapes = ", ".join("?" if s is None else str(tuple(s))
                           for s in self.input_shapes)
        loc = f"node {self.name!r} (op {self.op!r}"
        loc += f", input shapes [{shapes}])" if self.input_shapes else ")"
        return f"{self.rule} {loc}: {self.message}"


def format_issues(issues: List[GraphIssue]) -> str:
    return "\n".join(str(i) for i in issues)


def _as_struct(v):
    """NDArray / numpy array / ShapeDtypeStruct / shape tuple → struct."""
    import jax
    import numpy as np
    if isinstance(v, jax.ShapeDtypeStruct):
        return v
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))
    return jax.ShapeDtypeStruct(tuple(v), np.float32)


def validate_graph(sym, params: Optional[Dict[str, Any]] = None,
                   input_shapes: Optional[Dict[str, Any]] = None
                   ) -> List[GraphIssue]:
    """Statically check a Symbol graph; [] means valid.

    ``params`` maps var name → NDArray/numpy array (shape+dtype source);
    ``input_shapes`` maps var name → shape tuple or ShapeDtypeStruct.
    Stops at the first inconsistent node (everything downstream of a bad
    node would fail for derived reasons)."""
    var_structs: Dict[str, Any] = {}
    for k, v in (params or {}).items():
        var_structs[k] = _as_struct(v)
    for k, v in (input_shapes or {}).items():
        var_structs.setdefault(k, _as_struct(v))

    issues: List[GraphIssue] = []

    def on_error(node, in_structs, exc, missing):
        if missing is not None:
            what = "graph output var" if node.is_var() else "input"
            issues.append(GraphIssue(
                node.op, node.name,
                f"{what} {missing!r} has no shape — declare it via "
                f"input_shapes={{'{missing}': (...)}} or var(shape=...)"))
            return
        # _abstract_eval_node wraps the root cause in MXNetError; the
        # cause's first line is the actual shape/dtype complaint
        root = exc.__cause__ or exc
        msg = str(root).strip().splitlines()
        issues.append(GraphIssue(
            node.op, node.name, msg[0] if msg else repr(root),
            [tuple(s.shape) for s in in_structs]))

    sym._infer_structs_impl(var_structs, on_error=on_error)
    return issues
