"""Lockset sanitizer — the runtime half of mxlint's MXL203 (ISSUE 16).

Static analysis (:mod:`.deep`) derives the repo's lock-order graph
from the AST; this module validates it with dynamic evidence.
``install()`` (or ``MXTPU_ANALYSIS_LOCKCHECK=1`` at ``import mxtpu``)
patches the ``threading.Lock``/``threading.RLock`` factories so every
lock constructed afterwards is an :class:`InstrumentedLock` that
records, per thread, the order real acquisitions nest in. A violation
is reported when

- the same two locks are observed nesting in BOTH orders (a live
  deadlock window — two threads on those paths can each hold one and
  wait on the other), or
- an observed order contradicts the static lock graph: the graph has
  ``B -> A`` (some code path holds B while acquiring A) and never
  ``A -> B``, yet ``A -> B`` happened at runtime — either the static
  model is missing an edge (fix the model) or the code broke the
  global order the rest of the repo follows (fix the code).

The chaos tests are the intended driver: CI's ``lockcheck_smoke``
stage replays a gateway replica-kill test with the sanitizer on and
fails on any violation (zero expected — the serve stack's global
order is ``gateway -> replica-set -> engine``, journal lock leaf).

Names are inferred at construction by walking the stack to the
``__init__`` frame assigning the lock, so instrumented locks carry the
same ``Class.attr`` identity the static graph uses. Condition
aliasing is free at runtime: ``threading.Condition(self._lock)``
wraps the SAME instrumented object, so ``_cv`` waits/notifies record
against ``._lock``'s name.

Diagnostic-only: never enable in production serving (every
acquisition takes one extra dict hit under an internal mutex).
"""
from __future__ import annotations

import linecache
import os
import re
import sys
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = ["InstrumentedLock", "install", "uninstall", "installed",
           "reset", "observed_pairs", "violations", "assert_clean"]

_ENV = "MXTPU_ANALYSIS_LOCKCHECK"

# originals captured at install; the internal mutex is built from the
# ORIGINAL factory so the sanitizer never instruments itself
_orig: Dict[str, Any] = {}
_state_lock: Optional[Any] = None
_tls = threading.local()

# (held_name, acquired_name) -> first-seen "file:line in thread"
_pairs: Dict[Tuple[str, str], str] = {}


def _caller_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    # skip frames inside this module and threading.py (Condition
    # plumbing) so the site names USER code
    this = os.path.abspath(__file__)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != this and \
                not fn.endswith("threading.py"):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _infer_name() -> str:
    """``Class.attr`` for ``self._lock = threading.Lock()`` inside an
    ``__init__`` — the exact node id the static lock graph uses."""
    f = sys._getframe(2)
    first = f
    while f is not None:
        if f.f_code.co_name == "__init__" and "self" in f.f_locals:
            cls = type(f.f_locals["self"]).__name__
            line = linecache.getline(f.f_code.co_filename, f.f_lineno)
            m = re.search(r"self\.(\w+)\s*(?::[^=]+)?=", line)
            if m:
                return f"{cls}.{m.group(1)}"
            return f"{cls}.<lock@{f.f_lineno}>"
        f = f.f_back
    base = os.path.basename(first.f_code.co_filename)
    return f"{base}:{first.f_lineno}"


def _stack() -> List[str]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _note_acquired(name: str) -> None:
    s = _stack()
    if s and s[-1] != name:
        pair = (s[-1], name)
        with _state_lock:
            if pair not in _pairs:
                _pairs[pair] = (f"{_caller_site(3)} in "
                                f"{threading.current_thread().name}")
    s.append(name)


def _note_released(name: str) -> None:
    s = _stack()
    # locks release LIFO under ``with``, but tolerate hand-rolled
    # out-of-order release: drop the innermost matching entry
    for i in range(len(s) - 1, -1, -1):
        if s[i] == name:
            del s[i]
            return


class InstrumentedLock:
    """Drop-in wrapper over a real Lock/RLock that records per-thread
    acquisition order. Forwards the private ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol (with held-stack
    bookkeeping) so ``threading.Condition(instrumented_lock)`` works —
    a Condition ``wait`` releases every recursion level and the stack
    must mirror that."""

    def __init__(self, inner: Any, name: str):
        self._inner = inner
        self.name = name

    # -- core lock protocol ----------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _note_released(self.name)

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib modules (concurrent.futures.thread, threading's fork
        # handlers) re-init module-level locks in the child process;
        # the wrapper must forward or a post-install import of those
        # modules fails at attribute lookup
        self._inner._at_fork_reinit()

    # -- Condition plumbing ----------------------------------------------
    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            inner_state = self._inner._release_save()   # all levels
        else:
            self._inner.release()
            inner_state = None
        s = _stack()
        n = sum(1 for x in s if x == self.name)
        for i in range(len(s) - 1, -1, -1):
            if s[i] == self.name:
                del s[i]
        return (inner_state, n)

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        # re-entering after a wait is a real ordering event when other
        # locks are held; record once, then restore the levels
        _note_acquired(self.name)
        _stack().extend([self.name] * (n - 1))

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.name} of {self._inner!r}>"


# ---------------------------------------------------------------------------
# install / report
# ---------------------------------------------------------------------------
def installed() -> bool:
    return bool(_orig)


def install() -> None:
    """Patch the ``threading.Lock``/``RLock`` factories. Idempotent.
    Locks constructed BEFORE install are not instrumented — install
    early (the ``MXTPU_ANALYSIS_LOCKCHECK=1`` import hook runs before
    any mxtpu class can construct one)."""
    global _state_lock
    if _orig:
        return
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _state_lock = _orig["Lock"]()

    def _mk_lock():
        return InstrumentedLock(_orig["Lock"](), _infer_name())

    def _mk_rlock():
        return InstrumentedLock(_orig["RLock"](), _infer_name())

    threading.Lock = _mk_lock
    threading.RLock = _mk_rlock


def uninstall() -> None:
    if not _orig:
        return
    threading.Lock = _orig.pop("Lock")
    threading.RLock = _orig.pop("RLock")


def reset() -> None:
    _pairs.clear()


def observed_pairs() -> Dict[Tuple[str, str], str]:
    """(held, acquired) -> first-seen site, across all threads."""
    if _state_lock is None:
        return dict(_pairs)
    with _state_lock:
        return dict(_pairs)


def _static_edges(repo_root: Optional[str] = None
                  ) -> Optional[Set[Tuple[str, str]]]:
    """The static lock graph's edge set over ``mxtpu/`` — loaded by
    path (stdlib-only module) so this works under a patched
    ``threading`` without re-importing anything heavy."""
    import importlib.util
    here = os.path.dirname(os.path.abspath(__file__))
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(here)))
    pkg = os.path.join(root, "mxtpu")
    if not os.path.isdir(pkg):
        return None
    deep = sys.modules.get("_mxlint_deep")
    if deep is None:
        spec = importlib.util.spec_from_file_location(
            "_mxlint_deep", os.path.join(here, "deep.py"))
        deep = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = deep
        spec.loader.exec_module(deep)
    return set(deep.lock_graph_for([pkg]).edges)


def violations(static: bool = True,
               repo_root: Optional[str] = None) -> List[str]:
    """Order contradictions in what ran so far. ``static=True`` also
    cross-checks observed orders against the mxlint lock graph."""
    pairs = observed_pairs()
    out: List[str] = []
    for (a, b), site in sorted(pairs.items()):
        rev = pairs.get((b, a))
        if rev is not None and (b, a) > (a, b):
            continue                     # report each cycle pair once
        if rev is not None:
            out.append(
                f"lock-order inversion observed at runtime: "
                f"{a} -> {b} at {site} BUT {b} -> {a} at {rev} — "
                f"two threads on these paths can deadlock (MXL203)")
    if static:
        edges = _static_edges(repo_root)
        if edges:
            for (a, b), site in sorted(pairs.items()):
                if (b, a) in edges and (a, b) not in edges and \
                        (b, a) not in pairs:
                    out.append(
                        f"observed order {a} -> {b} (at {site}) "
                        f"contradicts the static lock graph, which "
                        f"only has {b} -> {a} — either the static "
                        f"model is missing an edge or this path "
                        f"broke the repo's global lock order "
                        f"(MXL203)")
    return out


def assert_clean(static: bool = True) -> None:
    """Raise AssertionError listing every violation (the CI smoke
    stage's teardown check)."""
    v = violations(static=static)
    assert not v, "lockcheck: %d violation(s):\n%s" % (
        len(v), "\n".join(v))
