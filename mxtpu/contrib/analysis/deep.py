"""mxlint deep pass — concurrency, determinism and runtime-contract
analysis over the serve/fleet/elastic stack (ISSUE 16 tentpole).

PR 6/7/15 review hardening kept finding the same bug families by hand:
dispatch-outside-lock, blocking-under-lock ("compile stalls
submitters"), stale-lock-window, metric label-set drift. Every instance
is statically visible in the AST, so this module turns that manual
review into a repeatable gate, three rule families deep:

- ``MXL2xx`` concurrency, from a per-class lock model (attributes
  assigned ``threading.Lock/RLock/Condition``, ``with self._lock:``
  scopes, thread-target methods):

  - ``MXL201`` — Eraser-style lockset: a shared attribute WRITTEN with
    no lock held in one method while the same attribute has
    lock-guarded accesses in another. Write-side only (unlocked reads
    of a published int are a different, far noisier conversation), and
    ``__init__`` is happens-before by construction so it never flags.
  - ``MXL202`` — blocking call under lock: ``time.sleep``, socket
    send/recv/accept/connect, framed-RPC round trips, ``queue.Queue``
    get/put, thread joins, foreign ``Event.wait`` and jitted-program
    dispatch inside a ``with``-lock body (the exact PR 6 "compile
    stalls submitters" class). ``Condition.wait`` on the lock it wraps
    RELEASES that lock and is exempt; a lock whose every with-body
    blocks is a dedicated I/O-serialization lock (the KV channel's
    send/recv locks) and is exempt as a whole.
  - ``MXL203`` — lock-order cycle over the inter-method acquisition
    graph: method A holds L1 and (directly, via a self-call, or via an
    unambiguous collaborator method) acquires L2, elsewhere reversed.
    Conditions alias the lock they wrap (``Condition(self._lock)``),
    so ``_cv``/``_lock`` are one graph node.

- ``MXL3xx`` determinism: ``MXL301`` raw ``jax.random.PRNGKey/split``
  on serve paths that must ride the ``serve.resume_key`` chain (the
  bit-identity oracle); ``MXL302`` raw ``time.time()/monotonic()``
  calls inside a class that HAS the injectable-clock idiom
  (``self._clock = clock or time.monotonic``) but bypasses it;
  ``MXL303`` unseeded ``np.random``/``mx.random`` module draws in
  tests and bench entrypoints.

- ``MXL4xx`` runtime contracts: ``MXL401`` one metric name used with
  differing label-key sets across call sites (the PR 15
  ``model``-label grandfathering class, enforced instead of
  hand-tested); ``MXL402`` every ``MXTPU_*`` env knob read in code
  must be registered in ``docs/env_var.md``.

The model's assumptions and limits are documented in docs/lint.md
(§"The lockset model"); the runtime half (:mod:`.lockcheck`)
cross-checks the static graph against real acquisition orders.

Suppression: the classic ``# mxlint: disable=MXL201`` comment works,
and so does ``# noqa: MXL201 — reason`` (IDs required; a bare
``# noqa`` does NOT suppress mxlint rules).

Stdlib-only, like :mod:`.rules`: ``python -m tools.mxlint --deep``
loads this file by path and never imports mxtpu or jax.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

# rules.py is the base engine; when this file is exec'd by file path
# (tools/mxlint) the relative import has no package, so fall back to
# the copy the CLI already loaded (or load it ourselves).
try:
    from .rules import (Finding, _collect_aliases, _dotted_chain,
                        _suppressions, iter_python_files)
except ImportError:                                   # path-loaded
    import importlib.util
    import sys
    _rules = sys.modules.get("_mxlint_rules")
    if _rules is None:
        _spec = importlib.util.spec_from_file_location(
            "_mxlint_rules",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "rules.py"))
        _rules = importlib.util.module_from_spec(_spec)
        sys.modules[_spec.name] = _rules
        _spec.loader.exec_module(_rules)
    Finding = _rules.Finding
    _collect_aliases = _rules._collect_aliases
    _dotted_chain = _rules._dotted_chain
    _suppressions = _rules._suppressions
    iter_python_files = _rules.iter_python_files

__all__ = ["DEEP_RULES", "deep_lint_paths", "deep_lint_file",
           "deep_lint_source", "build_lock_graph", "LockGraph"]

DEEP_RULES: Dict[str, str] = {
    "MXL201": "lockset: shared attribute written without the lock "
              "that guards its other accesses (Eraser-style "
              "write-side check)",
    "MXL202": "blocking call (sleep/socket/rpc/queue/join/jit "
              "dispatch) inside a with-lock body — stalls every "
              "thread contending for the lock",
    "MXL203": "lock-order cycle in the inter-method acquisition "
              "graph (deadlock risk)",
    "MXL301": "determinism: raw jax.random.PRNGKey/split on a serve "
              "path — route through the serve.resume_key chain",
    "MXL302": "determinism: raw time.time()/monotonic() in a class "
              "with an injectable clock (self._clock) — call the "
              "injected clock",
    "MXL303": "determinism: unseeded np.random/mx.random draw in a "
              "test or bench entrypoint",
    "MXL401": "runtime-contract: metric name used with differing "
              "label sets across call sites",
    "MXL402": "runtime-contract: MXTPU_* env knob read in code but "
              "not registered in docs/env_var.md",
}

# ``# noqa: MXL201 — reason`` / ``# noqa: MXL201, MXL302``: IDs are
# REQUIRED — a bare ``# noqa`` never suppresses mxlint rules (flake8's
# blanket form would hide findings silently).
_NOQA_RE = re.compile(r"#\s*noqa:\s*((?:MXL\d+[,\s]*)+)")


def _deep_suppressions(source: str) -> Dict[int, Set[str]]:
    out = _suppressions(source)
    for i, line in enumerate(source.splitlines(), start=1):
        m = _NOQA_RE.search(line)
        if m:
            out.setdefault(i, set()).update(
                re.findall(r"MXL\d+", m.group(1)))
    return out


# ---------------------------------------------------------------------------
# the per-class lock model
# ---------------------------------------------------------------------------
_SYNC_CTORS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition", "Semaphore": "semaphore",
               "BoundedSemaphore": "semaphore"}
_MUTATING_METHODS = {"append", "appendleft", "extend", "add", "insert",
                     "remove", "discard", "pop", "popleft", "clear",
                     "update", "setdefault", "reset", "sort",
                     "reverse", "fill"}
_SOCKET_BLOCKING = {"sendall", "sendto", "recv", "recv_into",
                    "recvfrom", "accept", "connect", "connect_ex",
                    "create_connection"}
_CLOCK_FNS = {"time", "monotonic"}       # perf_counter is exempt:
#                                          latency instrumentation
_RNG_DRAWS = {"rand", "randn", "randint", "random", "uniform",
              "normal", "choice", "shuffle", "permutation", "sample",
              "standard_normal", "randrange", "random_sample"}


def _self_attr(expr: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (one level only)."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self":
        return expr.attr
    return None


def _is_threading_ctor(node: ast.AST) -> Optional[Tuple[str, ast.Call]]:
    """``threading.Lock()`` / ``threading.Condition(x)`` -> (kind,
    call node)."""
    if not isinstance(node, ast.Call):
        return None
    chain = _dotted_chain(node.func)
    if chain is None:
        return None
    if chain[-1] in _SYNC_CTORS and (
            len(chain) == 1 or chain[-2] == "threading"):
        return _SYNC_CTORS[chain[-1]], node
    return None


@dataclass
class _Access:
    attr: str
    line: int
    col: int
    write: bool
    held: Tuple[str, ...]          # canonical lock names held
    method: str


@dataclass
class _Acquire:
    lock: str                      # canonical attr name
    line: int
    col: int
    held: Tuple[str, ...]          # held BEFORE this acquisition
    method: str


@dataclass
class _CallOut:
    recv_is_self: bool
    method_name: str               # callee method name
    line: int
    col: int
    held: Tuple[str, ...]
    method: str                    # calling method


@dataclass
class _Blocking:
    desc: str
    line: int
    col: int
    held: Tuple[str, ...]
    method: str
    lock_region: str               # innermost held lock
    io: bool = False               # socket/RPC round trip (vs
    #                                sleep/jit/queue/join)


@dataclass
class _Region:
    """One ``with self._lock:`` body."""
    blocked: bool                  # contains any blocking call
    io: bool                       # contains a socket/RPC call
    attrs: Set[str] = field(default_factory=set)


@dataclass
class _ClassModel:
    name: str
    path: str
    line: int
    sync_attrs: Dict[str, str] = field(default_factory=dict)
    cond_alias: Dict[str, str] = field(default_factory=dict)
    queue_attrs: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    jit_attrs: Set[str] = field(default_factory=set)
    clock_attr: Optional[str] = None
    accesses: List[_Access] = field(default_factory=list)
    acquires: List[_Acquire] = field(default_factory=list)
    calls_out: List[_CallOut] = field(default_factory=list)
    blocking: List[_Blocking] = field(default_factory=list)
    with_regions: Dict[str, List[_Region]] = field(default_factory=dict)
    methods: Set[str] = field(default_factory=set)

    def canon(self, attr: str) -> str:
        """Condition attrs alias the lock they wrap."""
        return self.cond_alias.get(attr, attr)


class _MethodScanner:
    """One pass over a method body tracking the held-lock stack."""

    def __init__(self, model: _ClassModel, method: str,
                 aliases: Dict[str, str]):
        self.m = model
        self.method = method
        self.aliases = aliases
        self.held: List[str] = []
        self.local_locks: Dict[str, str] = {}    # var -> lock attr
        self.local_jit: Set[str] = set()         # vars holding a
        #                                          jitted program

    # -- helpers ------------------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.m.sync_attrs:
            return self.m.canon(attr)
        if isinstance(expr, ast.Name) and expr.id in self.local_locks:
            return self.local_locks[expr.id]
        return None

    def _record_access(self, attr: str, node: ast.AST,
                       write: bool) -> None:
        self.m.accesses.append(_Access(
            attr, node.lineno, node.col_offset, write,
            tuple(self.held), self.method))

    def _blocking_desc(
            self, call: ast.Call) -> Optional[Tuple[str, bool]]:
        """(why this call blocks, is-socket/RPC-I/O), or None.
        Mirrors docs/lint.md."""
        chain = _dotted_chain(call.func)
        fn = call.func
        if chain is not None:
            # time.sleep
            if chain[-1] == "sleep" and len(chain) >= 2 and \
                    chain[-2] == "time":
                return "time.sleep(...)", False
            # framed-RPC round trip / reconnect helper
            if chain[-1] in ("call", "connect_with_backoff") and \
                    len(chain) >= 2 and chain[-2] == "rpc":
                return ".".join(chain) + "(...)", True
        if isinstance(fn, ast.Attribute):
            last = fn.attr
            recv_attr = _self_attr(fn.value)
            if last in _SOCKET_BLOCKING:
                return f".{last}()", True
            if last in ("get", "put") and recv_attr in \
                    self.m.queue_attrs:
                return f"queue .{last}()", False
            if last == "join" and recv_attr in self.m.thread_attrs:
                return "Thread.join()", False
            if last == "wait":
                if recv_attr is not None and \
                        recv_attr in self.m.sync_attrs and \
                        self.m.sync_attrs[recv_attr] == "condition" \
                        and self.m.canon(recv_attr) in self.held:
                    return None          # releases the lock it wraps
                if recv_attr in self.m.event_attrs:
                    return "Event.wait()", False
        # jitted dispatch: self._decode(...), fn(...) where fn came
        # off a jit-program attr, self._prefills[b](...)
        if isinstance(fn, ast.Attribute):
            a = _self_attr(fn)
            if a in self.m.jit_attrs:
                return f"jitted dispatch self.{a}(...)", False
        if isinstance(fn, ast.Subscript):
            a = _self_attr(fn.value)
            if a in self.m.jit_attrs:
                return f"jitted dispatch self.{a}[...](...)", False
        if isinstance(fn, ast.Name) and fn.id in self.local_jit:
            return f"jitted dispatch {fn.id}(...)", False
        return None

    def _scan_call(self, node: ast.Call) -> None:
        desc = self._blocking_desc(node)
        if self.held:
            region = self.held[-1]
            self.m.with_regions.setdefault(region, [])
            if desc is not None:
                self.m.blocking.append(_Blocking(
                    desc[0], node.lineno, node.col_offset,
                    tuple(self.held), self.method, region,
                    io=desc[1]))
        # call-out edges for the lock graph
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.m.calls_out.append(_CallOut(
                    True, fn.attr, node.lineno, node.col_offset,
                    tuple(self.held), self.method))
            elif not isinstance(recv, ast.Attribute) or \
                    _self_attr(recv) is not None or True:
                self.m.calls_out.append(_CallOut(
                    False, fn.attr, node.lineno, node.col_offset,
                    tuple(self.held), self.method))

    # -- statement walk -----------------------------------------------------
    def _scan_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._scan_call(sub)
            attr = None
            if isinstance(sub, ast.Attribute):
                attr = _self_attr(sub)
            if attr is not None and isinstance(sub.ctx, ast.Load):
                # mutating method call on self.attr counts as a write
                self._record_access(attr, sub, False)

    def _target_writes(self, target: ast.AST) -> None:
        if isinstance(target, ast.Attribute):
            attr = _self_attr(target)
            if attr is not None:
                self._record_access(attr, target, True)
            else:
                self._scan_expr(target.value)
        elif isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None:
                self._record_access(attr, target, True)
            else:
                self._scan_expr(target)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._target_writes(e)
        elif isinstance(target, ast.Starred):
            self._target_writes(target.value)

    def _note_mutating_calls(self, node: ast.AST) -> None:
        """``self.X.append(...)`` and friends are writes to X."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _MUTATING_METHODS:
                attr = _self_attr(sub.func.value)
                if attr is not None:
                    self._record_access(attr, sub, True)

    def _note_local_binds(self, stmt: ast.Assign) -> None:
        """Track locals bound to locks or jitted programs."""
        v = stmt.value
        lock = self._lock_of(v)
        names = [t.id for t in stmt.targets
                 if isinstance(t, ast.Name)]
        if lock is not None:
            for n in names:
                self.local_locks[n] = lock
            return
        is_jit = False
        if isinstance(v, ast.Call) and \
                isinstance(v.func, ast.Attribute) and \
                v.func.attr == "get":
            if _self_attr(v.func.value) in self.m.jit_attrs:
                is_jit = True
        if isinstance(v, ast.Subscript) and \
                _self_attr(v.value) in self.m.jit_attrs:
            is_jit = True
        if is_jit:
            self.local_jit.update(names)

    def run(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.With):
                pushed = 0
                for item in stmt.items:
                    lock = self._lock_of(item.context_expr)
                    self._scan_expr(item.context_expr)
                    if lock is not None:
                        self.m.acquires.append(_Acquire(
                            lock, stmt.lineno, stmt.col_offset,
                            tuple(self.held), self.method))
                        self.held.append(lock)
                        pushed += 1
                n_block = len(self.m.blocking)
                n_acc = len(self.m.accesses)
                self.run(stmt.body)
                if pushed:
                    region = self.held[-1]
                    mine = [b for b in self.m.blocking[n_block:]
                            if b.lock_region == region]
                    self.m.with_regions.setdefault(
                        region, []).append(_Region(
                            bool(mine), any(b.io for b in mine),
                            {a.attr
                             for a in self.m.accesses[n_acc:]}))
                for _ in range(pushed):
                    self.held.pop()
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # a nested def runs LATER (thread body, callback):
                # scan it with an empty held stack
                inner = _MethodScanner(
                    self.m, f"{self.method}.<locals>.{stmt.name}",
                    self.aliases)
                inner.run(stmt.body)
            elif isinstance(stmt, ast.ClassDef):
                continue
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign)):
                targets = (stmt.targets
                           if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if stmt.value is not None:
                    self._scan_expr(stmt.value)
                    self._note_mutating_calls(stmt.value)
                for t in targets:
                    self._target_writes(t)
                if isinstance(stmt, ast.Assign):
                    self._note_local_binds(stmt)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test)
                self._note_mutating_calls(stmt.test)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.For):
                self._scan_expr(stmt.iter)
                self._target_writes(stmt.target)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                self.run(stmt.body)
                for h in stmt.handlers:
                    self.run(h.body)
                self.run(stmt.orelse)
                self.run(stmt.finalbody)
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.Raise,
                                   ast.Assert, ast.Delete)):
                for v in ast.iter_child_nodes(stmt):
                    self._scan_expr(v)
                    self._note_mutating_calls(v)
                if isinstance(stmt, ast.Delete):
                    for t in stmt.targets:
                        self._target_writes(t)
            else:
                for v in ast.iter_child_nodes(stmt):
                    if isinstance(v, ast.expr):
                        self._scan_expr(v)


def _clock_idiom(value: ast.AST) -> bool:
    """``clock or time.monotonic`` / ``... if ... else time.time`` —
    the injectable-clock construction."""
    cands = []
    if isinstance(value, ast.BoolOp) and isinstance(value.op, ast.Or):
        cands = value.values
    elif isinstance(value, ast.IfExp):
        cands = [value.body, value.orelse]
    for c in cands:
        chain = _dotted_chain(c)
        if chain is not None and len(chain) == 2 and \
                chain[0] == "time" and chain[1] in _CLOCK_FNS:
            return True
    return False


def _scan_class(cls: ast.ClassDef, path: str,
                aliases: Dict[str, str]) -> _ClassModel:
    model = _ClassModel(cls.name, path, cls.lineno)
    # pass 1: attribute typing from every method (sync attrs are
    # normally in __init__ but replacement locks happen elsewhere)
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        model.methods.add(fn.name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                kind = _is_threading_ctor(node.value)
                if kind is not None:
                    model.sync_attrs[attr] = kind[0]
                    if kind[0] == "condition" and kind[1].args:
                        wrapped = _self_attr(kind[1].args[0])
                        if wrapped is not None:
                            model.cond_alias[attr] = wrapped
                    continue
                chain = _dotted_chain(node.value.func) \
                    if isinstance(node.value, ast.Call) else None
                if chain is not None:
                    if chain[-1] == "Queue":
                        model.queue_attrs.add(attr)
                    elif chain[-1] == "Event" and (
                            len(chain) == 1 or
                            chain[-2] == "threading"):
                        model.event_attrs.add(attr)
                    elif chain[-1] == "Thread":
                        model.thread_attrs.add(attr)
                    elif chain[-1] in ("jit", "watch", "pjit"):
                        model.jit_attrs.add(attr)
                if fn.name == "__init__" and _clock_idiom(node.value):
                    model.clock_attr = attr
        # dict caches of jitted programs:
        # ``self._prefills[bucket] = telemetry.watch(jax.jit(...))``
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        chain = (_dotted_chain(node.value.func)
                                 if isinstance(node.value, ast.Call)
                                 else None)
                        if attr is not None and chain is not None \
                                and chain[-1] in ("jit", "watch",
                                                  "pjit"):
                            model.jit_attrs.add(attr)
    # pass 2: method scan with the held-lock stack
    for fn in cls.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _MethodScanner(model, fn.name, aliases).run(fn.body)
    return model


# ---------------------------------------------------------------------------
# MXL201 — lockset (write side)
# ---------------------------------------------------------------------------
def _locked_helper_methods(model: _ClassModel) -> Set[str]:
    """Private methods whose every intra-class call site either holds
    a lock (directly or from another guarded helper) or sits in
    ``__init__`` (construction is single-threaded: happens-before
    thread start). Their bodies execute guarded, so their
    unlocked-looking accesses are too. ``_maybe_seal`` ("call with
    self._cond held") and ``_load_snapshot`` (called from ``__init__``
    before the accept loop spawns) are the two shapes."""
    sites: Dict[str, List[_CallOut]] = {}
    for c in model.calls_out:
        if c.recv_is_self and c.method_name in model.methods:
            sites.setdefault(c.method_name, []).append(c)

    def base(method: str) -> str:
        return method.split(".<locals>.")[0]

    locked: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, calls in sites.items():
            if name in locked or not name.startswith("_") or \
                    name.startswith("__"):
                continue
            if all(c.held or base(c.method) == "__init__" or
                   base(c.method) in locked for c in calls):
                locked.add(name)
                changed = True
    return locked


def _rule_lockset(model: _ClassModel) -> List[Finding]:
    if not model.sync_attrs:
        return []
    locked_helpers = _locked_helper_methods(model)

    def effective_held(a: _Access) -> bool:
        if a.held:
            return True
        base = a.method.split(".<locals>.")[0]
        return a.method in locked_helpers or base in locked_helpers

    by_attr: Dict[str, List[_Access]] = {}
    for a in model.accesses:
        if a.attr in model.sync_attrs or a.attr in model.queue_attrs \
                or a.attr in model.event_attrs \
                or a.attr in model.thread_attrs:
            continue                    # sync objects are self-safe
        by_attr.setdefault(a.attr, []).append(a)
    findings: List[Finding] = []
    for attr, accesses in sorted(by_attr.items()):
        guarded = [a for a in accesses if effective_held(a)]
        if not guarded:
            continue                    # never lock-protected: not ours
        guarded_methods = {a.method for a in guarded}
        seen_lines: Set[int] = set()
        for a in accesses:
            if not a.write or effective_held(a):
                continue
            if a.method == "__init__" or \
                    a.method.startswith("__init__.<locals>"):
                continue                # happens-before construction
            others = guarded_methods - {a.method}
            if not others or a.line in seen_lines:
                continue
            seen_lines.add(a.line)
            where = sorted(others)[0]
            findings.append(Finding(
                "MXL201", model.path, a.line, a.col,
                f"{model.name}.{attr} written in {a.method}() with no "
                f"lock held, but guarded by "
                f"{'/'.join(sorted(set(model.sync_attrs)))} in "
                f"{where}() — take the owning lock (or document with "
                f"# noqa: MXL201 — reason)"))
    return findings


# ---------------------------------------------------------------------------
# MXL202 — blocking call under lock
# ---------------------------------------------------------------------------
def _rule_blocking(model: _ClassModel) -> List[Finding]:
    if not model.blocking:
        return []
    # Dedicated I/O-serialization locks are the sanctioned exception
    # (KVChannel._send_lock, ElasticMember._lock): serializing the
    # channel is the lock's PURPOSE, so blocking on it is the design,
    # not a bug. Two shapes qualify:
    #   - every with-region of the lock blocks (pure framing lock):
    #     fully exempt;
    #   - every region touches one common channel attribute and at
    #     least one region does socket/RPC I/O on it: exempt for
    #     socket/RPC findings ONLY — a time.sleep or jit dispatch
    #     smuggled under the same lock still flags.
    full_exempt: Set[str] = set()
    io_exempt: Set[str] = set()
    for lock, regions in model.with_regions.items():
        if not regions:
            continue
        if all(r.blocked for r in regions):
            full_exempt.add(lock)
        common = set.intersection(*[r.attrs for r in regions])
        if common and any(r.io for r in regions):
            io_exempt.add(lock)
    findings: List[Finding] = []
    for b in model.blocking:
        if b.lock_region in full_exempt:
            continue
        if b.io and b.lock_region in io_exempt:
            continue
        findings.append(Finding(
            "MXL202", model.path, b.line, b.col,
            f"blocking {b.desc} while holding "
            f"{model.name}.{b.lock_region} in {b.method}() — every "
            f"thread contending for the lock stalls behind it; move "
            f"the blocking work outside the critical section (the "
            f"PR 6 two-phase admission pattern)"))
    return findings


# ---------------------------------------------------------------------------
# MXL203 — lock-order cycles over the global acquisition graph
# ---------------------------------------------------------------------------
@dataclass
class LockGraph:
    """The cross-class lock model: canonical nodes ``Class.attr``
    (Condition attrs aliased onto the lock they wrap), directed edges
    "held -> acquired" with their source sites. ``multi_lock_classes``
    = classes defining >= 2 sync attributes or holding one lock while
    (transitively) acquiring another."""
    nodes: Set[str] = field(default_factory=set)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = \
        field(default_factory=dict)
    aliases: Dict[str, str] = field(default_factory=dict)
    multi_lock_classes: Set[str] = field(default_factory=set)

    def add_edge(self, src: str, dst: str, path: str,
                 line: int) -> None:
        if src == dst:
            return
        self.nodes.update((src, dst))
        self.edges.setdefault((src, dst), (path, line))

    def cycle_edges(self) -> List[Tuple[str, str, str, int]]:
        """Edges participating in a cycle (both members of one
        strongly-connected component), with their sites."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        comp: Dict[str, int] = {}
        stack: List[str] = []
        counter = [0]
        ncomp = [0]
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)

        def strongconnect(v: str) -> None:
            work = [(v, iter(adj.get(v, ())))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(adj.get(w, ()))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp[w] = ncomp[0]
                        if w == node:
                            break
                    ncomp[0] += 1

        for v in sorted(self.nodes):
            if v not in index:
                strongconnect(v)
        sizes: Dict[int, int] = {}
        for v, c in comp.items():
            sizes[c] = sizes.get(c, 0) + 1
        out = []
        for (a, b), (path, line) in sorted(self.edges.items()):
            if comp.get(a) is not None and comp.get(a) == comp.get(b) \
                    and sizes.get(comp[a], 0) > 1:
                out.append((a, b, path, line))
        return out


def build_lock_graph(models: Sequence[_ClassModel]) -> LockGraph:
    graph = LockGraph()
    by_class = {m.name: m for m in models}
    for m in models:
        for attr, kind in m.sync_attrs.items():
            canon = m.canon(attr)
            graph.nodes.add(f"{m.name}.{canon}")
            if attr != canon:
                graph.aliases[f"{m.name}.{attr}"] = \
                    f"{m.name}.{canon}"
        if len(m.sync_attrs) >= 2:
            graph.multi_lock_classes.add(m.name)

    # (class, method) -> transitive lock-acquisition closure via
    # direct acquisitions and self-calls
    closure: Dict[Tuple[str, str], Set[str]] = {}

    def method_closure(cname: str, mname: str,
                       seen: Set[Tuple[str, str]]) -> Set[str]:
        key = (cname, mname)
        if key in closure:
            return closure[key]
        if key in seen:
            return set()
        seen.add(key)
        m = by_class.get(cname)
        out: Set[str] = set()
        if m is None:
            return out
        for acq in m.acquires:
            if acq.method.split(".<locals>.")[0] == mname:
                out.add(f"{cname}.{acq.lock}")
        for c in m.calls_out:
            if c.recv_is_self and \
                    c.method.split(".<locals>.")[0] == mname and \
                    c.method_name in m.methods:
                out |= method_closure(cname, c.method_name, seen)
        closure[key] = out
        return out

    for m in models:
        for mm in m.methods:
            method_closure(m.name, mm, set())

    # duck resolution, frozen on the round-1 closures: a non-self call
    # ``x.m()`` resolves iff exactly ONE scanned class's ``m`` has a
    # non-empty acquisition closure (ambiguous names — submit, route —
    # are skipped: a wrong candidate would fabricate cycles)
    duck: Dict[str, Optional[Tuple[str, Set[str]]]] = {}
    all_names: Dict[str, List[str]] = {}
    for m in models:
        for mm in m.methods:
            all_names.setdefault(mm, []).append(m.name)
    for name, classes in all_names.items():
        acquirers = [(c, closure[(c, name)]) for c in classes
                     if closure.get((c, name))]
        duck[name] = acquirers[0] if len(acquirers) == 1 else None

    # second closure pass: self-calls + resolved duck calls
    full: Dict[Tuple[str, str], Set[str]] = {}

    def full_closure(cname: str, mname: str,
                     seen: Set[Tuple[str, str]]) -> Set[str]:
        key = (cname, mname)
        if key in full:
            return full[key]
        if key in seen:
            return set()
        seen.add(key)
        m = by_class.get(cname)
        out: Set[str] = set(closure.get(key, set()))
        if m is None:
            return out
        for c in m.calls_out:
            if c.method.split(".<locals>.")[0] != mname:
                continue
            if c.recv_is_self and c.method_name in m.methods:
                out |= full_closure(cname, c.method_name, seen)
            elif not c.recv_is_self:
                r = duck.get(c.method_name)
                if r is not None and r[0] != cname:
                    out |= full_closure(r[0], c.method_name, seen)
        full[key] = out
        return out

    # edges: direct nested acquisition + held-across-call acquisition
    for m in models:
        for acq in m.acquires:
            if acq.held:
                graph.add_edge(f"{m.name}.{acq.held[-1]}",
                               f"{m.name}.{acq.lock}",
                               m.path, acq.line)
                graph.multi_lock_classes.add(m.name)
        for c in m.calls_out:
            if not c.held:
                continue
            targets: Set[str] = set()
            if c.recv_is_self and c.method_name in m.methods:
                targets = full_closure(m.name, c.method_name, set())
            elif not c.recv_is_self:
                r = duck.get(c.method_name)
                if r is not None and r[0] != m.name:
                    targets = full_closure(r[0], c.method_name, set())
            held_node = f"{m.name}.{c.held[-1]}"
            for t in sorted(targets):
                if t != held_node:
                    graph.add_edge(held_node, t, m.path, c.line)
                    graph.multi_lock_classes.add(m.name)
    return graph


def _rule_lock_order(models: Sequence[_ClassModel]) -> List[Finding]:
    graph = build_lock_graph(models)
    findings = []
    for a, b, path, line in graph.cycle_edges():
        findings.append(Finding(
            "MXL203", path, line, 0,
            f"lock-order cycle: {a} is held while acquiring {b}, and "
            f"elsewhere the order is reversed — a thread on each path "
            f"deadlocks; pick ONE global order (docs/lint.md "
            f"§MXL203)"))
    return findings


# ---------------------------------------------------------------------------
# MXL3xx — determinism
# ---------------------------------------------------------------------------
def _is_serve_path(path: str, tree: ast.AST) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    if "serve" in parts:
        return True
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("mxtpu.serve") or mod == "mxtpu" and \
                    any(a.name == "serve" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.startswith("mxtpu.serve")
                   for a in node.names):
                return True
    return False


def _rule_serve_rng(tree: ast.AST, aliases: Dict[str, str],
                    path: str) -> List[Finding]:
    if not _is_serve_path(path, tree):
        return []
    if os.path.basename(path).startswith("bench"):
        return []          # bench harnesses derive keys from --seed:
        #                    deterministic by construction, and MXL303
        #                    owns entrypoint seeding discipline
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _dotted_chain(node.func)
        if chain is None or len(chain) < 2:
            continue
        if chain[-1] in ("PRNGKey", "split") and \
                chain[-2] == "random" and \
                aliases.get(chain[0], chain[0]).split(".")[0] == "jax":
            findings.append(Finding(
                "MXL301", path, node.lineno, node.col_offset,
                f"raw jax.random.{chain[-1]} on a serve path breaks "
                f"the bit-identity oracle across crash re-dispatch — "
                f"derive keys from the serve.resume_key chain (or "
                f"mark the chain root with # noqa: MXL301 — reason)"))
    return findings


def _rule_raw_clock(tree: ast.AST, path: str) -> List[Finding]:
    findings = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        clock_attr = None
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None and _clock_idiom(node.value):
                        clock_attr = attr
        if clock_attr is None:
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted_chain(node.func)
            if chain is not None and len(chain) == 2 and \
                    chain[0] == "time" and chain[1] in _CLOCK_FNS:
                findings.append(Finding(
                    "MXL302", path, node.lineno, node.col_offset,
                    f"raw time.{chain[1]}() inside {cls.name}, which "
                    f"has the injectable clock self.{clock_attr} — "
                    f"call self.{clock_attr}() so tests can "
                    f"single-step time"))
    return findings


def _is_test_or_bench(path: str) -> bool:
    base = os.path.basename(path)
    parts = os.path.normpath(path).split(os.sep)
    return (base.startswith("test_") or base.startswith("bench")
            or base.endswith("_test.py") or "tests" in parts)


def _rule_unseeded_rng(tree: ast.AST, aliases: Dict[str, str],
                       path: str) -> List[Finding]:
    if not _is_test_or_bench(path):
        return []
    seeded = False
    draws: List[Tuple[ast.Call, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _dotted_chain(node.func)
        if chain is None:
            continue
        root = aliases.get(chain[0], chain[0]).split(".")[0]
        if chain[-1] == "seed" and root in ("numpy", "np", "mxtpu",
                                            "mx", "random"):
            seeded = True
        elif chain[-1] == "default_rng" and node.args:
            seeded = True                # explicit generator seed
        elif chain[-1] == "default_rng" and not node.args:
            draws.append((node, "default_rng()"))
        elif chain[-1] in _RNG_DRAWS and len(chain) >= 2 and \
                chain[-2] == "random" and root in ("numpy", "np",
                                                   "mxtpu", "mx"):
            draws.append((node, ".".join(chain)))
        elif chain[-1] in _RNG_DRAWS and len(chain) == 2 and \
                chain[0] == "random" and root == "random":
            draws.append((node, ".".join(chain)))
    if seeded:
        return []
    return [Finding(
        "MXL303", path, n.lineno, n.col_offset,
        f"unseeded {desc} in a test/bench entrypoint — seed the "
        f"module (np.random.seed / default_rng(seed)) so reruns "
        f"reproduce (the PR 2/3 neural-style flake class)")
        for n, desc in draws]


# ---------------------------------------------------------------------------
# MXL4xx — runtime contracts (cross-file)
# ---------------------------------------------------------------------------
@dataclass
class _MetricSite:
    name: str
    keys: Tuple[str, ...]
    has_star: bool
    path: str
    line: int
    col: int


def _metric_sites(tree: ast.AST, path: str) -> List[_MetricSite]:
    sites = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _dotted_chain(node.func)
        if chain is None or chain[-1] not in ("counter", "gauge",
                                              "histogram"):
            continue
        if len(chain) >= 2 and "telemetry" not in chain[0] and \
                chain[-2] != "telemetry":
            continue
        if len(chain) == 1:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        keys = tuple(sorted(kw.arg for kw in node.keywords
                            if kw.arg is not None))
        star = any(kw.arg is None for kw in node.keywords)
        sites.append(_MetricSite(node.args[0].value, keys, star,
                                 path, node.lineno, node.col_offset))
    return sites


def _rule_metric_labels(sites: Sequence[_MetricSite]) -> List[Finding]:
    by_name: Dict[str, List[_MetricSite]] = {}
    for s in sites:
        by_name.setdefault(s.name, []).append(s)
    findings = []
    for name, group in sorted(by_name.items()):
        static = [s for s in group if not s.has_star]
        if len(static) < 2:
            continue          # **labels sites are dynamic: unverifiable
        counts: Dict[Tuple[str, ...], int] = {}
        for s in static:
            counts[s.keys] = counts.get(s.keys, 0) + 1
        if len(counts) == 1:
            continue
        ordered = sorted(static, key=lambda s: (s.path, s.line))
        consensus = max(
            counts.items(),
            key=lambda kv: (kv[1], kv[0] == ordered[0].keys))[0]
        for s in ordered:
            if s.keys != consensus:
                findings.append(Finding(
                    "MXL401", s.path, s.line, s.col,
                    f"metric {name!r} created here with label set "
                    f"{list(s.keys)} but {list(consensus)} at its "
                    f"other call sites — one series, one label "
                    f"schema (define a shared helper like "
                    f"serve.cancel_counter)"))
    return findings


_ENV_READERS = {"env_float", "env_int", "env_str", "env_bool",
                "getenv"}


@dataclass
class _EnvRead:
    name: str
    path: str
    line: int
    col: int


def _env_reads(tree: ast.AST, path: str) -> List[_EnvRead]:
    reads = []

    def const_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith("MXTPU_"):
            return node.value
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _dotted_chain(node.func)
            if chain is None:
                continue
            if chain[-1] in _ENV_READERS and node.args:
                name = const_name(node.args[0])
                if name:
                    reads.append(_EnvRead(name, path, node.lineno,
                                          node.col_offset))
            elif chain[-1] == "get" and len(chain) >= 3 and \
                    chain[-2] == "environ" and node.args:
                name = const_name(node.args[0])
                if name:
                    reads.append(_EnvRead(name, path, node.lineno,
                                          node.col_offset))
        elif isinstance(node, ast.Subscript):
            chain = _dotted_chain(node.value)
            if chain is not None and chain[-1] == "environ":
                name = const_name(node.slice)
                if name:
                    reads.append(_EnvRead(name, path, node.lineno,
                                          node.col_offset))
    return reads


_REGISTRY_CACHE: Dict[str, Optional[Tuple[Set[str],
                                          Tuple[str, ...]]]] = {}


def _env_registry(start: str):
    """(exact names, wildcard prefixes) from the nearest
    docs/env_var.md above ``start``; None when no registry exists
    (linting outside a repo — the rule stands down)."""
    d = os.path.abspath(start if os.path.isdir(start)
                        else os.path.dirname(start))
    walked = []
    while True:
        if d in _REGISTRY_CACHE:
            reg = _REGISTRY_CACHE[d]
            break
        walked.append(d)
        cand = os.path.join(d, "docs", "env_var.md")
        if os.path.isfile(cand):
            with open(cand, encoding="utf-8", errors="replace") as f:
                text = f.read()
            exact = set(re.findall(r"MXTPU_[A-Z0-9_]+", text))
            wild = tuple(p for p in
                         re.findall(r"(MXTPU_[A-Z0-9_]+_)\*", text))
            reg = (exact, wild)
            break
        parent = os.path.dirname(d)
        if parent == d:
            reg = None
            break
        d = parent
    for w in walked:
        _REGISTRY_CACHE[w] = reg
    return reg


def _rule_env_drift(reads: Sequence[_EnvRead]) -> List[Finding]:
    findings = []
    for r in reads:
        reg = _env_registry(r.path)
        if reg is None:
            continue
        exact, wild = reg
        if r.name in exact or any(r.name.startswith(p) for p in wild):
            continue
        findings.append(Finding(
            "MXL402", r.path, r.line, r.col,
            f"env knob {r.name} is read here but not registered in "
            f"docs/env_var.md — every MXTPU_* knob must be in the "
            f"config reference (add a table row)"))
    return findings


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------
class _DeepRun:
    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.models: List[_ClassModel] = []
        self.metric_sites: List[_MetricSite] = []
        self.env_reads: List[_EnvRead] = []
        self.suppress: Dict[str, Dict[int, Set[str]]] = {}

    def add_source(self, source: str, path: str) -> None:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return                       # the base pass reports MXL000
        self.suppress[path] = _deep_suppressions(source)
        aliases = _collect_aliases(tree)
        models = [_scan_class(c, path, aliases)
                  for c in ast.walk(tree)
                  if isinstance(c, ast.ClassDef)]
        self.models.extend(models)
        for m in models:
            self.findings += _rule_lockset(m)
            self.findings += _rule_blocking(m)
        self.findings += _rule_serve_rng(tree, aliases, path)
        self.findings += _rule_raw_clock(tree, path)
        self.findings += _rule_unseeded_rng(tree, aliases, path)
        self.metric_sites += _metric_sites(tree, path)
        self.env_reads += _env_reads(tree, path)

    def add_file(self, path: str) -> None:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            self.add_source(f.read(), path)

    def finalize(self,
                 rules: Optional[Sequence[str]] = None
                 ) -> List[Finding]:
        findings = list(self.findings)
        findings += _rule_lock_order(self.models)
        findings += _rule_metric_labels(self.metric_sites)
        findings += _rule_env_drift(self.env_reads)
        if rules is not None:
            wanted = {r.upper() for r in rules}
            findings = [f for f in findings if f.rule in wanted]
        out = []
        for f in findings:
            sup = self.suppress.get(f.path, {})
            if {f.rule, "ALL"} & sup.get(f.line, set()):
                continue
            out.append(f)
        out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return out


def deep_lint_paths(paths: Sequence[str],
                    rules: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """Run the deep pass (MXL2xx/3xx/4xx) over every ``.py`` under
    ``paths``. Cross-file rules (MXL203 duck resolution, MXL401
    consensus, MXL402 registry) see the whole run at once."""
    run = _DeepRun()
    for f in iter_python_files(paths):
        run.add_file(f)
    return run.finalize(rules)


def deep_lint_file(path: str,
                   rules: Optional[Sequence[str]] = None
                   ) -> List[Finding]:
    run = _DeepRun()
    run.add_file(path)
    return run.finalize(rules)


def deep_lint_source(source: str, path: str = "<string>",
                     rules: Optional[Sequence[str]] = None
                     ) -> List[Finding]:
    run = _DeepRun()
    run.add_source(source, path)
    return run.finalize(rules)


def lock_graph_for(paths: Sequence[str]) -> LockGraph:
    """The cross-class lock model for ``paths`` — the static half the
    runtime sanitizer (:mod:`.lockcheck`) checks observed acquisition
    orders against, and what tests assert coverage on."""
    run = _DeepRun()
    for f in iter_python_files(paths):
        run.add_file(f)
    return build_lock_graph(run.models)
