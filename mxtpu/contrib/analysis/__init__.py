"""mxtpu.contrib.analysis — the mxlint static-analysis suite.

Two halves:

- AST rules over Python source (:mod:`.rules`): trace-safety
  (``MXL001``), tracer-control-flow (``MXL002``), dispatch-count
  (``MXL003``). Run them with :func:`lint_paths` or the CLI,
  ``python -m tools.mxlint mxtpu/ example/``.
- Graph validity over traced ``Symbol`` programs (:mod:`.graph`,
  ``MXL100``): static shape/dtype inference that reports the first
  inconsistent node with op name and inferred shapes; reused by the
  ONNX exporter and exposed as ``Symbol.validate()``.
- The deep pass (:mod:`.deep`): whole-repo lockset/lock-order
  analysis (``MXL201``-``MXL203``), determinism (``MXL301``-``MXL303``)
  and runtime-contract drift (``MXL401``/``MXL402``). Run with
  ``python -m tools.mxlint --deep``. Its dynamic counterpart is
  :mod:`.lockcheck` — ``MXTPU_ANALYSIS_LOCKCHECK=1`` instruments every
  lock and cross-checks real acquisition orders against the static
  lock graph.

See docs/lint.md for rule semantics and the suppression syntax.
"""
from .rules import (RULES, Finding, iter_python_files, lint_file,
                    lint_paths, lint_source)
from .graph import GraphIssue, format_issues, validate_graph
from .deep import (DEEP_RULES, LockGraph, deep_lint_file,
                   deep_lint_paths, deep_lint_source, lock_graph_for)

__all__ = ["RULES", "Finding", "lint_source", "lint_file", "lint_paths",
           "iter_python_files", "GraphIssue", "validate_graph",
           "format_issues", "DEEP_RULES", "deep_lint_source",
           "deep_lint_file", "deep_lint_paths", "lock_graph_for",
           "LockGraph"]
