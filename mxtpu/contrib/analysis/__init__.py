"""mxtpu.contrib.analysis — the mxlint static-analysis suite.

Two halves:

- AST rules over Python source (:mod:`.rules`): trace-safety
  (``MXL001``), tracer-control-flow (``MXL002``), dispatch-count
  (``MXL003``). Run them with :func:`lint_paths` or the CLI,
  ``python -m tools.mxlint mxtpu/ example/``.
- Graph validity over traced ``Symbol`` programs (:mod:`.graph`,
  ``MXL100``): static shape/dtype inference that reports the first
  inconsistent node with op name and inferred shapes; reused by the
  ONNX exporter and exposed as ``Symbol.validate()``.

See docs/lint.md for rule semantics and the suppression syntax.
"""
from .rules import (RULES, Finding, iter_python_files, lint_file,
                    lint_paths, lint_source)
from .graph import GraphIssue, format_issues, validate_graph

__all__ = ["RULES", "Finding", "lint_source", "lint_file", "lint_paths",
           "iter_python_files", "GraphIssue", "validate_graph",
           "format_issues"]
