"""mxlint AST rules — trace-safety static analysis over mxtpu user code.

The round-5 regression that motivated this pass: ``HybridConcatenate.
hybrid_forward`` hardcoded ``nd.concat`` instead of routing through the
``F`` parameter, so every ``hybridize()``/export trace died at runtime.
That is a *class* of bug — backend calls that bypass ``F``, Python
control flow on tracer values, per-parameter dispatch loops on the hot
path — and every instance is statically visible in the AST. These rules
catch the whole class at lint time, before a device or a trace is ever
involved.

Rules (stable IDs, see docs/lint.md):

- ``MXL001`` trace-safety: a hardcoded ``nd.*``/``np.*``/``jnp.*`` call
  (any alias of an ndarray/numpy backend module) inside a
  ``hybrid_forward`` body. Under a symbolic or jit trace the inputs are
  Symbols/tracers, so the eager backend call either crashes or silently
  constant-folds; route through ``F`` instead.
- ``MXL002`` tracer-control-flow: ``if``/``while``/``assert`` whose
  condition derives from a tensor argument of ``hybrid_forward``.
  Truthiness of a traced tensor breaks ``hybridize()``/jit. Static
  facts (``x.shape``, ``x.ndim``, ``x.dtype``, ``x is None``,
  ``isinstance(x, ...)``) are fine and not flagged.
- ``MXL003`` dispatch-count: a per-parameter Python loop dispatching
  optimizer/ndarray ops inside a ``step``/``update`` path — the
  ~150-dispatches-per-step pattern ``Trainer.make_fused_step`` exists
  to kill.
- ``MXL004`` serving-latency: a host synchronization (``.item()``,
  ``float()``/``int()`` on a tensor, ``np.asarray``,
  ``.block_until_ready()``, ``jax.device_get``, ``.asnumpy()``)
  inside a decode/generate loop body — the classic serving-latency
  bug: the host blocks on every token and the accelerator pipeline
  drains. Flagged when the loop's enclosing function is decode/
  generate/serve-named OR the loop body itself dispatches a
  decode/generate call. Fix: read tokens back one step late so the
  sync overlaps the next step's compute (the ``mxtpu.serve`` engine's
  pattern — docs/serving.md), or batch the readback after the loop.

Suppression: append ``# mxlint: disable=MXL001`` (comma-separate for
several IDs, or ``disable=all``) to the flagged line, or put the comment
alone on the line directly above it.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["RULES", "Finding", "lint_source", "lint_file", "lint_paths",
           "iter_python_files"]

RULES: Dict[str, str] = {
    "MXL000": "parse-error: file does not parse as Python",
    "MXL001": "trace-safety: hardcoded backend call inside hybrid_forward "
              "(route through the F parameter)",
    "MXL002": "tracer-control-flow: Python control flow on a tensor value "
              "inside hybrid_forward (breaks hybridize()/jit)",
    "MXL003": "dispatch-count: per-parameter Python op loop in a "
              "step/update path (use Trainer.make_fused_step)",
    "MXL004": "serving-latency: host sync inside a decode/generate "
              "loop body (overlap or batch the readback — "
              "docs/serving.md)",
    "MXL100": "graph-validity: Symbol graph fails static shape/dtype "
              "inference (see mxtpu.contrib.analysis.validate_graph)",
}


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------
_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\s]+)")


def _suppressions(source: str) -> Dict[int, Set[str]]:
    """line number → set of disabled rule IDs (or {'all'}). A disable
    comment covers its own line; a standalone disable comment also
    covers the next line."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        ids = {s.strip().upper() for s in m.group(1).split(",") if s.strip()}
        out.setdefault(i, set()).update(ids)
        if line.split("#", 1)[0].strip() == "":  # comment-only line
            out.setdefault(i + 1, set()).update(ids)
    return out


# ---------------------------------------------------------------------------
# import alias resolution
# ---------------------------------------------------------------------------
# module paths whose calls produce/consume concrete arrays (not F-routed).
# Matching is on the dotted path: the last segment, or any segment for
# 'ndarray' (so relative imports like ``from .. import ndarray as nd``
# and deep ones like ``mxtpu.ndarray.random`` both match).
_TENSOR_LAST_SEGMENTS = {"ndarray", "numpy", "nd", "jnp", "numpy_extension"}


def _is_tensor_module(dotted: str) -> bool:
    parts = [p for p in dotted.split(".") if p]
    if not parts:
        return False
    return parts[-1] in _TENSOR_LAST_SEGMENTS or "ndarray" in parts \
        or "numpy" in parts


def _collect_aliases(tree: ast.AST) -> Dict[str, str]:
    """name bound by an import → the dotted module/object path it names.
    Relative imports keep their leading dots stripped (segment matching
    only cares about the trailing path)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                aliases[bound] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                aliases[bound] = f"{mod}.{a.name}" if mod else a.name
    return aliases


def _dotted_chain(expr: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a", "b", "c"]; None when the root is not a Name."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return parts[::-1]


def _expand_callee_module(chain: List[str],
                          aliases: Dict[str, str]) -> Optional[str]:
    """Dotted path of the MODULE a call resolves into, with the root
    alias expanded — ``nd.concat`` → ``mxtpu.ndarray``, ``mx.nd.concat``
    → ``mxtpu.nd``, ``concat`` (imported from mxtpu.ndarray) →
    ``mxtpu.ndarray``. None when the root is not an import alias."""
    root = chain[0]
    if root not in aliases:
        return None
    expanded = aliases[root].split(".") + chain[1:]
    return ".".join(expanded[:-1]) if len(expanded) > 1 else expanded[0]


# ---------------------------------------------------------------------------
# hybrid_forward discovery
# ---------------------------------------------------------------------------
def _hybrid_forwards(tree: ast.AST) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "hybrid_forward"]


def _tensor_params(fn: ast.FunctionDef) -> Set[str]:
    """The tensor arguments of hybrid_forward(self, F, x, *args,
    **params): everything after (self, F), including defaults, kw-only
    args, *args and **kwargs (parameters arrive through **kwargs)."""
    names = [a.arg for a in fn.args.args[2:]]
    names += [a.arg for a in fn.args.kwonlyargs]
    if fn.args.vararg is not None:
        names.append(fn.args.vararg.arg)
    if fn.args.kwarg is not None:
        names.append(fn.args.kwarg.arg)
    return set(names)


# ---------------------------------------------------------------------------
# MXL001 — trace-safety
# ---------------------------------------------------------------------------
def _rule_trace_safety(tree: ast.AST, aliases: Dict[str, str],
                       path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _hybrid_forwards(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted_chain(node.func)
            if chain is None:
                continue
            module = _expand_callee_module(chain, aliases)
            if module is None or not _is_tensor_module(module):
                continue
            callee = ".".join(chain)
            findings.append(Finding(
                "MXL001", path, node.lineno, node.col_offset,
                f"hardcoded backend call {callee}() inside hybrid_forward "
                f"resolves to module {module!r}; use the F parameter so "
                f"the op traces (F.{chain[-1]}(...))"))
    return findings


# ---------------------------------------------------------------------------
# MXL002 — tracer control flow
# ---------------------------------------------------------------------------
# attribute reads that are static under a trace (shape metadata, not data)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "context", "ctx",
                 "stype", "name", "grad_req"}
# calls whose result is trace-static regardless of argument taint
_STATIC_CALLS = {"len", "isinstance", "issubclass", "hasattr", "getattr",
                 "type", "id", "repr", "str"}


class _TaintChecker:
    """Conservative forward taint pass over one hybrid_forward body."""

    def __init__(self, tainted: Set[str]):
        self.tainted = set(tainted)

    # -- expression taint ---------------------------------------------------
    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            chain = _dotted_chain(node.func)
            if chain is not None and chain[0] in _STATIC_CALLS \
                    and len(chain) == 1:
                return False
            # a call taints if its function or any argument taints
            # (F.relu(x), x.sum(), tainted_fn(...))
            parts = [node.func] + list(node.args) + \
                [kw.value for kw in node.keywords]
            return any(self.expr_tainted(p) for p in parts)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False  # identity checks are static under trace
            return self.expr_tainted(node.left) or \
                any(self.expr_tainted(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.expr_tainted(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) or \
                self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return self.expr_tainted(node.body) or \
                self.expr_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        return False

    # -- statement walk -----------------------------------------------------
    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)
        # attribute/subscript targets don't (un)taint names

    def run(self, body: Sequence[ast.stmt], path: str,
            findings: List[Finding]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                t = self.expr_tainted(stmt.value)
                for target in stmt.targets:
                    self._bind(target, t)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._bind(stmt.target, self.expr_tainted(stmt.value))
            elif isinstance(stmt, ast.AugAssign):
                if self.expr_tainted(stmt.value):
                    self._bind(stmt.target, True)
            elif isinstance(stmt, (ast.If, ast.While)):
                if self.expr_tainted(stmt.test):
                    kw = "while" if isinstance(stmt, ast.While) else "if"
                    findings.append(Finding(
                        "MXL002", path, stmt.lineno, stmt.col_offset,
                        f"`{kw}` condition derives from a hybrid_forward "
                        f"tensor argument — truthiness of a traced tensor "
                        f"breaks hybridize()/jit (use F.where or restructure"
                        f" on static facts like .shape)"))
                self.run(stmt.body, path, findings)
                self.run(stmt.orelse, path, findings)
            elif isinstance(stmt, ast.Assert):
                if self.expr_tainted(stmt.test):
                    findings.append(Finding(
                        "MXL002", path, stmt.lineno, stmt.col_offset,
                        "`assert` on a hybrid_forward tensor argument — "
                        "the check evaluates a traced tensor and breaks "
                        "hybridize()/jit"))
            elif isinstance(stmt, ast.For):
                self._bind(stmt.target, self.expr_tainted(stmt.iter))
                self.run(stmt.body, path, findings)
                self.run(stmt.orelse, path, findings)
            elif isinstance(stmt, (ast.With,)):
                self.run(stmt.body, path, findings)
            elif isinstance(stmt, ast.Try):
                self.run(stmt.body, path, findings)
                for h in stmt.handlers:
                    self.run(h.body, path, findings)
                self.run(stmt.orelse, path, findings)
                self.run(stmt.finalbody, path, findings)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                pass
            # nested defs/classes start a new scope — skip


def _rule_tracer_flow(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _hybrid_forwards(tree):
        checker = _TaintChecker(_tensor_params(fn))
        checker.run(fn.body, path, findings)
    return findings


# ---------------------------------------------------------------------------
# MXL003 — per-parameter dispatch loops
# ---------------------------------------------------------------------------
_STEP_FN_RE = re.compile(r"^_?(step|update)(_multi_precision)?$")
# optimizer-op callees: sgd_update, sgd_mom_update, adam_update,
# mp_lamb_update, ... plus anything called through an updater/optimizer
_OPT_OP_RE = re.compile(
    r"^(mp_)?(sgd|adam|adamw|rmsprop|adagrad|adadelta|lamb|ftrl|nag|"
    r"signsgd|signum|dcasgd|lars)\w*_update\w*$")


def _callee_last(call: ast.Call) -> Tuple[Optional[str], List[str]]:
    chain = _dotted_chain(call.func)
    if chain is None:
        return None, []
    return chain[-1], chain


def _loop_dispatches_updates(loop: ast.AST) -> Optional[str]:
    """Does this loop body dispatch a per-parameter optimizer update?
    Returns a short description of the offending call, or None."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        last, chain = _callee_last(node)
        if last is None:
            continue
        receiver = chain[:-1]
        if "updater" in last or _OPT_OP_RE.match(last):
            return ".".join(chain)
        if last in ("update", "update_multi_precision") and any(
                "optimizer" in seg or seg in ("_opt", "opt")
                for seg in receiver):
            return ".".join(chain)
    return None


def _loop_is_param_update(loop: ast.For) -> bool:
    """The user-code shape of the pattern: iterate parameters, body does
    ``p.set_data(... p.grad() ...)`` — one eager dispatch chain per
    parameter per step."""
    it = ast.unparse(loop.iter)
    if "param" not in it.lower():
        return False
    body_src = "".join(ast.unparse(s) for s in loop.body)
    return ".set_data(" in body_src and ".grad(" in body_src


def _rule_dispatch_count(tree: ast.AST, path: str) -> List[Finding]:
    findings: List[Finding] = []
    flagged: Set[int] = set()

    def emit(node: ast.AST, offender: str) -> None:
        if id(node) in flagged:
            return
        flagged.add(id(node))
        findings.append(Finding(
            "MXL003", path, node.lineno, node.col_offset,
            f"per-parameter Python loop dispatches {offender} on the "
            f"step/update hot path (~one device dispatch per parameter "
            f"per step); fuse with Trainer.make_fused_step(net)"))

    # (a) updater/optimizer-op calls looped inside a step/update function
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or not _STEP_FN_RE.match(fn.name):
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.While)):
                offender = _loop_dispatches_updates(node)
                if offender is not None:
                    emit(node, offender)
    # (b) the user-script shape, anywhere (module level included):
    # iterate parameters, set_data(grad...) each
    for node in ast.walk(tree):
        if isinstance(node, ast.For) and _loop_is_param_update(node):
            emit(node, "set_data(.. .grad() ..) per parameter")
    return findings


# ---------------------------------------------------------------------------
# MXL004 — host syncs inside decode/generate loops
# ---------------------------------------------------------------------------
# function names that mark a serving/decoding context on their own
# (anchored at a word/underscore start: "imdecode" — the image codec —
# must not qualify)
_SERVE_FN_RE = re.compile(r"(?:^|_)(decode|generate|serve)",
                          re.IGNORECASE)
# callee last-segments that mark a loop body as a decode loop; the
# caller additionally requires >= 2 call arguments so ``bytes
# .decode()`` / ``s.decode("utf-8")`` never qualify
_DECODE_CALL_RE = re.compile(r"(?:^|_)(decode|generate)",
                             re.IGNORECASE)
# method calls that force a device->host sync on their receiver
_SYNC_ATTRS = {"item", "block_until_ready", "asnumpy"}
# host-numpy entry points that force a sync on a device-array argument
_HOST_NP_FUNCS = {"asarray", "array"}


def _sync_call_desc(node: ast.Call, aliases: Dict[str, str],
                    weak: bool) -> Optional[str]:
    """A short description of why this call is a host sync, or None.
    ``weak`` additionally counts ``float()``/``int()`` on a
    non-constant — only safe to assume tensor-ish when the loop
    provably dispatches decode/generate (the colocation context); in
    the name-only context they are far more often host-value parses."""
    chain = _dotted_chain(node.func)
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}()"
    if weak and chain is not None and len(chain) == 1 and \
            chain[0] in ("float", "int") and len(node.args) == 1 and \
            not isinstance(node.args[0], ast.Constant):
        return f"{chain[0]}(...)"
    if chain is None:
        return None
    if chain[-1] == "device_get":
        return ".".join(chain) + "(...)"
    module = _expand_callee_module(chain, aliases)
    if module is not None and chain[-1] in _HOST_NP_FUNCS and \
            "numpy" in module.split(".") and \
            module.split(".")[0] != "jax":
        return ".".join(chain) + "(...)"
    return None


def _loop_calls_decode(loop: ast.AST) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            chain = _dotted_chain(node.func)
            if chain is not None and \
                    _DECODE_CALL_RE.search(chain[-1]) and \
                    len(node.args) + len(node.keywords) >= 2:
                return True
    return False


def _rule_serving_sync(tree: ast.AST, aliases: Dict[str, str],
                       path: str) -> List[Finding]:
    findings: List[Finding] = []
    flagged: Set[int] = set()

    def scan_loops(scope: ast.AST, fn_name: str) -> None:
        for loop in ast.walk(scope):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            colocated = _loop_calls_decode(loop)
            in_context = colocated or \
                bool(_SERVE_FN_RE.search(fn_name))
            if not in_context:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or \
                        id(node) in flagged:
                    continue
                desc = _sync_call_desc(node, aliases, weak=colocated)
                if desc is None:
                    continue
                flagged.add(id(node))
                findings.append(Finding(
                    "MXL004", path, node.lineno, node.col_offset,
                    f"host sync {desc} inside a decode/generate loop "
                    f"body blocks the accelerator every iteration; "
                    f"read results back one step late (overlap) or "
                    f"batch the readback after the loop "
                    f"(docs/serving.md)"))

    # loops inside functions carry the function's name as context;
    # module-level loops qualify only via the decode-call heuristic
    covered: Set[int] = set()
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_loops(fn, fn.name)
            for n in ast.walk(fn):
                covered.add(id(n))
    for node in ast.iter_child_nodes(tree):
        if id(node) not in covered:
            scan_loops(node, "")
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the AST rules over one source blob. ``rules`` filters to a
    subset of rule IDs (default: all)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("MXL000", path, e.lineno or 1, e.offset or 0,
                        f"file does not parse: {e.msg}")]
    aliases = _collect_aliases(tree)
    findings: List[Finding] = []
    findings += _rule_trace_safety(tree, aliases, path)
    findings += _rule_tracer_flow(tree, path)
    findings += _rule_dispatch_count(tree, path)
    findings += _rule_serving_sync(tree, aliases, path)
    if rules is not None:
        wanted = {r.upper() for r in rules}
        findings = [f for f in findings if f.rule in wanted]
    sup = _suppressions(source)
    findings = [f for f in findings
                if not ({f.rule, "ALL"} & sup.get(f.line, set()))]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path=path, rules=rules)


_SKIP_DIRS = {"__pycache__", ".git", ".tox", ".venv", "node_modules",
              "build", "dist"}


def iter_python_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS and
                             not d.startswith("."))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every ``.py`` under the given files/directories."""
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings += lint_file(f, rules=rules)
    return findings
