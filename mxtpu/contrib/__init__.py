"""mx.contrib (reference ``python/mxnet/contrib/``): control flow, amp,
quantization entry points."""
from ..ndarray.contrib import foreach, while_loop, cond
from ..ndarray.contrib_ops import *   # noqa: F401,F403

__all__ = ["foreach", "while_loop", "cond", "amp"]


def __getattr__(name):
    import importlib
    if name == "amp":
        return importlib.import_module("mxtpu.amp")
    if name == "quantization":
        try:
            return importlib.import_module("mxtpu.contrib.quantization")
        except ModuleNotFoundError:
            raise AttributeError(
                "mxtpu.contrib.quantization is not available in this "
                "build") from None
    if name == "text":
        return importlib.import_module("mxtpu.contrib.text")
    if name in ("deploy", "summary", "tensorboard"):
        return importlib.import_module(
            "mxtpu.contrib.summary" if name == "tensorboard"
            else f"mxtpu.contrib.{name}")
    if name == "onnx":
        return importlib.import_module("mxtpu.contrib.onnx")
    if name == "analysis":
        return importlib.import_module("mxtpu.contrib.analysis")
    if name == "chaos":
        return importlib.import_module("mxtpu.contrib.chaos")
    raise AttributeError(f"module 'mxtpu.contrib' has no attribute {name!r}")
