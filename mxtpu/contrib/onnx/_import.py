"""ONNX → mxtpu graph importer (the onnx2mx direction).

Rebuild of the reference's ``python/mxnet/contrib/onnx/onnx2mx``
[path cite — unverified]: walk the ONNX graph's nodes and rebuild each
as a Symbol op through a converter registry. Initializers become
parameter NDArrays; BatchNormalization's running stats land in
``aux_params`` (matching the reference's arg/aux split).

Opset semantics target 13+ (per-axis Softmax, axes-as-inputs for
Squeeze/Unsqueeze/ReduceSum); attr-style axes from older opsets are
accepted where they are unambiguous.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as _np

from . import onnx_pb2 as _pb
from ._export import tensor_to_np, _ONNX2NP

_IMPORTERS: Dict[str, Callable] = {}


def imports(*op_types):
    def deco(fn):
        for t in op_types:
            _IMPORTERS[t] = fn
        return fn
    return deco


class _Ctx:
    """Per-import state: value name → Symbol, plus constant lookup for
    inputs that must be compile-time values (shapes, axes, pads...)."""

    def __init__(self, sym_mod, consts: Dict[str, _np.ndarray]):
        self.sym = sym_mod
        self.values: Dict[str, Any] = {}
        self.consts = consts  # initializer/Constant values by name

    def const(self, name: str, what: str) -> _np.ndarray:
        if name not in self.consts:
            raise ValueError(
                f"{what}: input {name!r} must be a constant "
                f"(initializer or Constant node) to import")
        return self.consts[name]

    def maybe_const(self, name: Optional[str]):
        return self.consts.get(name) if name else None


def _opt(ins, i):
    """i-th input or None — ONNX encodes absent optional inputs as ""
    (mapped to None), so presence means BOTH in range and non-None."""
    return ins[i] if len(ins) > i and ins[i] is not None else None


def _attrs(node) -> Dict[str, Any]:
    out = {}
    for a in node.attribute:
        if a.type == _pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == _pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == _pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == _pb.AttributeProto.INTS:
            out[a.name] = [int(x) for x in a.ints]
        elif a.type == _pb.AttributeProto.FLOATS:
            out[a.name] = [float(x) for x in a.floats]
        elif a.type == _pb.AttributeProto.TENSOR:
            out[a.name] = tensor_to_np(a.t)
        else:
            out[a.name] = a
    return out


def _sym_pad_pair(pads: Optional[List[int]], nd: int,
                  what: str) -> Tuple[List[int], Optional[List[int]]]:
    """ONNX [begin..., end...] pads → (symmetric mxtpu pad, or explicit
    flat pad_width when asymmetric)."""
    if not pads:
        return [0] * nd, None
    begin, end = pads[:nd], pads[nd:]
    if begin == end:
        return [int(p) for p in begin], None
    pw = [0, 0, 0, 0]  # N, C
    for b, e in zip(begin, end):
        pw += [int(b), int(e)]
    return [0] * nd, pw


def _check_auto_pad(at, what):
    ap = at.get("auto_pad", "NOTSET")
    if ap not in ("NOTSET", "VALID"):  # VALID ≡ explicit zero pads
        raise ValueError(f"{what}: auto_pad={ap!r} unsupported — "
                         f"re-export with explicit pads")


@imports("Conv")
def _conv(ctx, node, ins, at):
    _check_auto_pad(at, "Conv")
    w = ctx.maybe_const(node.input[1])
    kernel = at.get("kernel_shape")
    if kernel is None:
        if w is None:
            raise ValueError("Conv without kernel_shape needs const weight")
        kernel = list(w.shape[2:])
    nd = len(kernel)
    group = int(at.get("group", 1))
    pad, pw = _sym_pad_pair(at.get("pads"), nd, "Conv")
    data = ins[0]
    if pw is not None:
        data = ctx.sym.pad(data, mode="constant", pad_width=tuple(pw))
    num_filter = w.shape[0] if w is not None else None
    return ctx.sym.Convolution(
        data, ins[1], _opt(ins, 2),
        kernel=tuple(int(k) for k in kernel),
        stride=tuple(at.get("strides", [1] * nd)),
        dilate=tuple(at.get("dilations", [1] * nd)),
        pad=tuple(pad), num_filter=num_filter, num_group=group,
        no_bias=_opt(ins, 2) is None)


@imports("ConvTranspose")
def _conv_transpose(ctx, node, ins, at):
    kernel = at.get("kernel_shape")
    if kernel is None:
        w = ctx.const(node.input[1], "ConvTranspose weight")
        kernel = list(w.shape[2:])
    nd = len(kernel)
    if at.get("output_shape") or at.get("auto_pad", "NOTSET") != "NOTSET":
        raise ValueError("ConvTranspose output_shape/auto_pad unsupported")
    pad, pw = _sym_pad_pair(at.get("pads"), nd, "ConvTranspose")
    if pw is not None:
        raise ValueError("asymmetric ConvTranspose pads unsupported")
    return ctx.sym.Deconvolution(
        ins[0], ins[1], _opt(ins, 2),
        kernel=tuple(int(k) for k in kernel),
        stride=tuple(at.get("strides", [1] * nd)),
        dilate=tuple(at.get("dilations", [1] * nd)),
        pad=tuple(pad),
        adj=tuple(at.get("output_padding", [0] * nd)),
        num_group=int(at.get("group", 1)),
        no_bias=_opt(ins, 2) is None)


@imports("Gemm")
def _gemm(ctx, node, ins, at):
    alpha, beta = at.get("alpha", 1.0), at.get("beta", 1.0)
    transA, transB = at.get("transA", 0), at.get("transB", 0)
    if alpha == 1.0 and beta == 1.0 and not transA and transB:
        return ctx.sym.FullyConnected(
            ins[0], ins[1], _opt(ins, 2),
            no_bias=_opt(ins, 2) is None, flatten=False)
    a, b = ins[0], ins[1]
    y = ctx.sym.dot(a, b, transpose_a=bool(transA), transpose_b=bool(transB))
    if alpha != 1.0:
        y = y * alpha
    c = _opt(ins, 2)
    if c is not None:
        y = ctx.sym.broadcast_add(y, c * beta if beta != 1.0 else c)
    return y


@imports("MatMul")
def _matmul(ctx, node, ins, at):
    # mxtpu `dot` (contract lhs-last/rhs-first) == MatMul for rhs ≤ 2-D;
    # SymbolBlock abstract-eval will surface rank mismatches if the model
    # actually feeds batched rhs — those import as batch_dot by hand.
    return ctx.sym.dot(ins[0], ins[1])


_ACT = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
        "Softplus": "softrelu", "Softsign": "softsign"}


def _act(ctx, node, ins, at):
    return ctx.sym.Activation(ins[0], act_type=_ACT[node.op_type])


for _t in _ACT:
    _IMPORTERS[_t] = _act


@imports("LeakyRelu")
def _leaky(ctx, node, ins, at):
    return ctx.sym.LeakyReLU(ins[0], act_type="leaky",
                             slope=at.get("alpha", 0.01))


@imports("Elu")
def _elu(ctx, node, ins, at):
    return ctx.sym.LeakyReLU(ins[0], act_type="elu",
                             slope=at.get("alpha", 1.0))


@imports("Selu")
def _selu(ctx, node, ins, at):
    return ctx.sym.LeakyReLU(ins[0], act_type="selu")


@imports("PRelu")
def _prelu(ctx, node, ins, at):
    return ctx.sym.LeakyReLU(ins[0], gamma=ins[1], act_type="prelu")


@imports("Erf")
def _erf(ctx, node, ins, at):
    return ctx.sym.erf(ins[0])


@imports("Softmax")
def _softmax(ctx, node, ins, at):
    return ctx.sym.softmax(ins[0], axis=at.get("axis", -1))


@imports("LogSoftmax")
def _log_softmax(ctx, node, ins, at):
    return ctx.sym.log_softmax(ins[0], axis=at.get("axis", -1))


@imports("MaxPool", "AveragePool")
def _pool(ctx, node, ins, at):
    kernel = at["kernel_shape"]
    nd = len(kernel)
    _check_auto_pad(at, node.op_type)
    pt = "max" if node.op_type == "MaxPool" else "avg"
    pad, pw = _sym_pad_pair(at.get("pads"), nd, node.op_type)
    data = ins[0]
    if pw is not None:
        if pt == "max":
            raise ValueError("asymmetric MaxPool pads unsupported")
        if not at.get("count_include_pad", 0):
            # pre-padding zeros would silently include them in the mean
            raise ValueError("asymmetric AveragePool pads with "
                             "count_include_pad=0 unsupported")
        data = ctx.sym.pad(data, mode="constant", pad_width=tuple(pw))
        pad = [0] * nd
    return ctx.sym.Pooling(
        data, kernel=tuple(int(k) for k in kernel), pool_type=pt,
        stride=tuple(at.get("strides", [1] * nd)),
        pad=tuple(pad),
        pooling_convention="full" if at.get("ceil_mode") else "valid",
        count_include_pad=bool(at.get("count_include_pad", 0)))


@imports("GlobalMaxPool", "GlobalAveragePool")
def _global_pool(ctx, node, ins, at):
    pt = "max" if node.op_type == "GlobalMaxPool" else "avg"
    return ctx.sym.Pooling(ins[0], global_pool=True, pool_type=pt)


@imports("BatchNormalization")
def _bn(ctx, node, ins, at):
    # inference semantics: normalize with the provided running stats
    return ctx.sym.BatchNorm(
        ins[0], ins[1], ins[2], ins[3], ins[4],
        eps=at.get("epsilon", 1e-5), momentum=at.get("momentum", 0.9),
        use_global_stats=True)


@imports("LayerNormalization")
def _ln(ctx, node, ins, at):
    return ctx.sym.LayerNorm(
        ins[0], ins[1],
        _opt(ins, 2) if _opt(ins, 2) is not None
        else ctx.sym.zeros_like(ins[1]),
        axis=at.get("axis", -1), eps=at.get("epsilon", 1e-5))


@imports("LRN")
def _lrn(ctx, node, ins, at):
    return ctx.sym.LRN(ins[0], alpha=at.get("alpha", 1e-4),
                       beta=at.get("beta", 0.75),
                       knorm=at.get("bias", 1.0), nsize=at["size"])


@imports("Dropout")
def _dropout(ctx, node, ins, at):
    # opset ≥ 12 carries ratio as the optional second input (a constant
    # scalar); older opsets use the attribute; default 0.5 per the spec.
    # A PRESENT ratio input that is a runtime tensor must fail loudly —
    # silently training the re-imported model at 0.5 would corrupt it.
    if len(node.input) > 1 and node.input[1]:
        p = float(_np.asarray(
            ctx.const(node.input[1], "Dropout ratio")).reshape(()))
    else:
        p = at.get("ratio", 0.5)
    return ctx.sym.Dropout(ins[0], p=p)


@imports("Identity")
def _identity(ctx, node, ins, at):
    return ctx.sym.identity(ins[0])


_BIN = {"Add": "broadcast_add", "Sub": "broadcast_sub",
        "Mul": "broadcast_mul", "Div": "broadcast_div",
        "Pow": "broadcast_power"}


def _bin(ctx, node, ins, at):
    return getattr(ctx.sym, _BIN[node.op_type])(ins[0], ins[1])


for _t in _BIN:
    _IMPORTERS[_t] = _bin


@imports("Mod")
def _mod(ctx, node, ins, at):
    if at.get("fmod"):
        # C fmod (sign of dividend): a - trunc(a/b)*b — jnp.mod is
        # floor-mod and would flip the sign for negative dividends
        q = ctx.sym.trunc(ctx.sym.broadcast_div(ins[0], ins[1]))
        return ctx.sym.broadcast_sub(
            ins[0], ctx.sym.broadcast_mul(q, ins[1]))
    return ctx.sym.broadcast_mod(ins[0], ins[1])


@imports("Max", "Min")
def _maxmin(ctx, node, ins, at):
    op = "broadcast_maximum" if node.op_type == "Max" else "broadcast_minimum"
    y = ins[0]
    for x in ins[1:]:
        y = getattr(ctx.sym, op)(y, x)
    return y


@imports("Sum")
def _sum_n(ctx, node, ins, at):
    return ctx.sym.add_n(*ins) if len(ins) > 1 else ctx.sym.identity(ins[0])


_CMP = {"Greater": "broadcast_greater", "Less": "broadcast_lesser",
        "Equal": "broadcast_equal",
        "GreaterOrEqual": "broadcast_greater_equal",
        "LessOrEqual": "broadcast_lesser_equal"}


def _cmp(ctx, node, ins, at):
    # mxtpu comparisons return 0/1 in the operand dtype; ONNX returns
    # bool — downstream Cast/Where handle either
    return getattr(ctx.sym, _CMP[node.op_type])(ins[0], ins[1])


for _t in _CMP:
    _IMPORTERS[_t] = _cmp


@imports("Not")
def _not(ctx, node, ins, at):
    return 1.0 - ins[0]


_UN = {"Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Neg": "negative",
       "Abs": "abs", "Floor": "floor", "Ceil": "ceil", "Round": "round",
       "Sign": "sign", "Sin": "sin", "Cos": "cos",
       "Reciprocal": "reciprocal"}


def _un(ctx, node, ins, at):
    return getattr(ctx.sym, _UN[node.op_type])(ins[0])


for _t in _UN:
    _IMPORTERS[_t] = _un


@imports("Cast")
def _cast(ctx, node, ins, at):
    return ctx.sym.cast(ins[0], dtype=_ONNX2NP[at["to"]])


@imports("Clip")
def _clip(ctx, node, ins, at):
    if len(node.input) > 1:  # opset 11+: min/max as inputs
        def bound(i):
            name = node.input[i] if len(node.input) > i else ""
            if not name:
                return None
            v = ctx.const(name, "Clip bound")  # raises if a runtime tensor
            return None if not _np.isfinite(v).all() else float(v)
        a_min, a_max = bound(1), bound(2)
    else:  # opset < 11: attrs
        a_min, a_max = at.get("min"), at.get("max")
    return ctx.sym.clip(ins[0], a_min=a_min, a_max=a_max)


@imports("Concat")
def _concat(ctx, node, ins, at):
    return ctx.sym.concat(*ins, dim=at.get("axis", 0))


@imports("Reshape")
def _reshape(ctx, node, ins, at):
    if len(node.input) > 1:
        shape = ctx.const(node.input[1], "Reshape shape")
    else:  # opset 1-4 attr form
        shape = _np.asarray(at["shape"])
    if at.get("allowzero"):
        raise ValueError("Reshape(allowzero=1) unsupported")
    return ctx.sym.reshape(ins[0], shape=tuple(int(s) for s in shape))


@imports("Flatten")
def _flatten(ctx, node, ins, at):
    axis = at.get("axis", 1)
    if axis == 1:
        return ctx.sym.Flatten(ins[0])
    if axis == 0:
        return ctx.sym.reshape(ins[0], shape=(1, -1))
    raise ValueError(f"Flatten(axis={axis}) unsupported")


@imports("Transpose")
def _transpose(ctx, node, ins, at):
    perm = at.get("perm")
    return ctx.sym.transpose(ins[0], axes=tuple(perm) if perm else None)


@imports("Unsqueeze")
def _unsqueeze(ctx, node, ins, at):
    axes = ctx.const(node.input[1], "Unsqueeze axes") \
        if len(node.input) > 1 else _np.asarray(at["axes"])
    # ONNX axes index the OUTPUT rank. Rank-agnostic ordering: front
    # inserts (positive axes, ascending) never shift back-relative
    # positions, and back inserts (negative axes, descending — closest
    # to -1 first) never shift front or deeper-negative positions.
    axes = [int(a) for a in axes]
    y = ins[0]
    for a in sorted(a for a in axes if a >= 0):
        y = ctx.sym.expand_dims(y, axis=a)
    for a in sorted((a for a in axes if a < 0), reverse=True):
        y = ctx.sym.expand_dims(y, axis=a)
    return y


@imports("Squeeze")
def _squeeze(ctx, node, ins, at):
    if len(node.input) > 1:
        axes = ctx.const(node.input[1], "Squeeze axes")
        return ctx.sym.squeeze(ins[0], axis=tuple(int(a) for a in axes))
    if "axes" in at:
        return ctx.sym.squeeze(ins[0], axis=tuple(at["axes"]))
    return ctx.sym.squeeze(ins[0])


@imports("Slice")
def _slice(ctx, node, ins, at):
    if len(node.input) > 1:
        starts = ctx.const(node.input[1], "Slice starts")
        ends = ctx.const(node.input[2], "Slice ends")
        axes = ctx.const(node.input[3], "Slice axes") \
            if len(node.input) > 3 else _np.arange(len(starts))
        steps = ctx.const(node.input[4], "Slice steps") \
            if len(node.input) > 4 else _np.ones(len(starts), _np.int64)
    else:  # opset < 10 attr form
        starts = _np.asarray(at["starts"])
        ends = _np.asarray(at["ends"])
        axes = _np.asarray(at.get("axes", list(range(len(starts)))))
        steps = _np.ones(len(starts), _np.int64)
    y = ins[0]
    big = 2 ** 31  # clamp ONNX's INT64_MAX-style "to the end" sentinels
    for s, e, a, st in zip(starts, ends, axes, steps):
        if int(st) != 1:
            raise ValueError("Slice with step != 1 unsupported")
        if int(s) == 0 and int(e) >= big:
            continue  # full-range no-op on this axis
        # python-slice clamping makes an over-large end equal open-ended
        y = ctx.sym.slice_axis(y, axis=int(a), begin=int(s),
                               end=min(int(e), big - 1))
    return y


@imports("Gather")
def _gather(ctx, node, ins, at):
    # mode="wrap": ONNX Gather allows negative (from-the-end) indices,
    # which modulo-wrap reproduces; the take default "clip" would clamp
    # them to index 0
    return ctx.sym.take(ins[0], ins[1], axis=at.get("axis", 0),
                        mode="wrap")


@imports("Where")
def _where(ctx, node, ins, at):
    return ctx.sym.where(ins[0], ins[1], ins[2])


_RED = {"ReduceMean": "mean", "ReduceMax": "max", "ReduceMin": "min",
        "ReduceProd": "prod", "ReduceSum": "sum"}


def _reduce(ctx, node, ins, at):
    if len(node.input) > 1 and node.input[1]:  # axes input (opset 13+)
        axes = tuple(int(a) for a in ctx.const(node.input[1],
                                               f"{node.op_type} axes"))
    else:
        axes = tuple(at["axes"]) if "axes" in at else None
    if axes == ():  # empty axes = reduce all, unless noop flag is set
        if at.get("noop_with_empty_axes"):
            return ctx.sym.identity(ins[0])
        axes = None
    return getattr(ctx.sym, _RED[node.op_type])(
        ins[0], axis=axes, keepdims=bool(at.get("keepdims", 1)))


for _t in _RED:
    _IMPORTERS[_t] = _reduce


@imports("Pad")
def _pad(ctx, node, ins, at):
    if len(node.input) > 3 and node.input[3]:
        # opset 18 added an axes input (pads cover only those axes) —
        # len(pads)//2 below would pad the wrong dims silently
        raise ValueError("Pad with axes input unsupported")
    if len(node.input) > 1:
        pads = ctx.const(node.input[1], "Pad pads")
        cval = ctx.const(node.input[2], "Pad constant_value") \
            if len(node.input) > 2 and node.input[2] else None
    else:
        pads = _np.asarray(at["pads"])
        cval = at.get("value", 0.0)
    nd = len(pads) // 2
    pw = []
    for i in range(nd):
        pw += [int(pads[i]), int(pads[i + nd])]
    return ctx.sym.pad(ins[0], mode=at.get("mode", "constant"),
                       pad_width=tuple(pw),
                       constant_value=0.0 if cval is None else float(cval))


@imports("Split")
def _split(ctx, node, ins, at):
    axis = at.get("axis", 0)
    n = len(node.output)
    sizes = None
    if len(node.input) > 1 and node.input[1]:  # opset 13+: sizes as input
        sizes = ctx.const(node.input[1], "Split sizes")
    elif "split" in at:
        sizes = at["split"]
    if sizes is not None and len(set(int(s) for s in sizes)) != 1:
        raise ValueError("unequal Split unsupported")
    return ctx.sym.split(ins[0], num_outputs=n, axis=axis)


@imports("Constant")
def _constant(ctx, node, ins, at):
    raise AssertionError("Constant nodes are folded before conversion")


def import_graph(model: _pb.ModelProto):
    """ModelProto → (Symbol, arg_params, aux_params, input_names)."""
    import mxtpu.symbol as sym_mod
    import mxtpu.ndarray as nd

    g = model.graph
    init_np = {t.name: tensor_to_np(t) for t in g.initializer}

    # fold Constant nodes into the initializer table
    nodes = []
    for n in g.node:
        if n.op_type == "Constant":
            at = _attrs(n)
            if "value" not in at:
                raise ValueError("Constant without tensor value unsupported")
            init_np[n.output[0]] = at["value"]
        else:
            nodes.append(n)

    # running stats (BatchNormalization inputs 3,4) are aux, rest are args
    aux_names = set()
    for n in nodes:
        if n.op_type == "BatchNormalization":
            aux_names.update(n.input[3:5])

    ctx = _Ctx(sym_mod, init_np)
    input_names = []
    for vi in g.input:
        if vi.name in init_np:
            continue  # pre-IR4 models list initializers as inputs too
        ctx.values[vi.name] = sym_mod.var(vi.name)
        input_names.append(vi.name)

    def value(name: str):
        if name in ctx.values:
            return ctx.values[name]
        if name in init_np:
            v = sym_mod.var(name, aux=name in aux_names)
            ctx.values[name] = v
            return v
        raise ValueError(f"value {name!r} referenced before definition")

    for n in nodes:
        fn = _IMPORTERS.get(n.op_type)
        if fn is None:
            raise ValueError(
                f"ONNX op {n.op_type!r} has no mxtpu importer; "
                f"supported: {sorted(_IMPORTERS)}")
        at = _attrs(n)
        # converters receive a Symbol for every input; structural inputs
        # (shapes/axes/pads) are read via ctx.const() instead and their
        # unused placeholder symbols never enter the graph
        ins = [value(nm) if nm else None for nm in n.input]
        out = fn(ctx, n, ins, at)
        if isinstance(out, (list, tuple)):
            outs = list(out)
        elif len(n.output) > 1:
            # multi-entry Symbol (e.g. Split) — but a node may also
            # declare OPTIONAL extra outputs (Dropout mask, MaxPool
            # indices) the converter doesn't produce; leave those
            # unbound so only an actual consumer errors, by name
            n_avail = len(out)
            outs = [out[i] for i in range(min(len(n.output), n_avail))]
        else:
            outs = [out]
        for name, s in zip(n.output, outs):
            if name:
                ctx.values[name] = s

    heads = [ctx.values[vi.name] for vi in g.output]
    sym = sym_mod.Group(heads) if len(heads) > 1 else heads[0]

    # only keep params the final graph actually references
    referenced = set(sym.list_arguments()) | \
        set(sym.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in init_np.items()
                  if k in referenced and k not in aux_names}
    aux_params = {k: nd.array(v) for k, v in init_np.items()
                  if k in referenced and k in aux_names}
    return sym, arg_params, aux_params, input_names
