"""ONNX interchange for mxtpu — export Symbol/HybridBlock graphs to
ONNX and import ONNX models back.

Rebuild of the reference's ``python/mxnet/contrib/onnx/`` (mx2onnx +
onnx2mx) [path cite — unverified], with one environment-driven
difference: the ``onnx`` pip package is not available here, so the ONNX
IR schema ships with this package (``onnx.proto``, transcribed from the
public spec) and is compiled locally — see README.md in this directory
for what that does and does not validate.

Public surface (mirrors the reference):
- ``export_model(sym, params, input_shapes, onnx_file)`` → path
- ``import_model(model_file)`` → (sym, arg_params, aux_params)
- ``import_to_gluon(model_file, ctx=None)`` → SymbolBlock
- ``get_model_metadata(model_file)`` → input/output shapes
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from . import onnx_pb2
from ._export import export_graph, make_tensor, tensor_to_np
from ._import import import_graph

__all__ = ["export_model", "import_model", "import_to_gluon",
           "get_model_metadata", "onnx_pb2"]


def _normalize_shapes(sym, params, input_shapes):
    """Accept dict or positional list of shapes for the graph inputs."""
    if input_shapes is None:
        return None
    if isinstance(input_shapes, dict):
        return {k: tuple(v) for k, v in input_shapes.items()}
    inputs = [n for n in sym.list_inputs() if n not in params]
    if len(inputs) != len(input_shapes):
        raise ValueError(
            f"{len(input_shapes)} shapes for {len(inputs)} inputs {inputs}")
    return dict(zip(inputs, (tuple(s) for s in input_shapes)))


def export_model(sym, params=None, input_shapes=None,
                 onnx_file: str = "model.onnx", opset: int = 13,
                 verbose: bool = False) -> str:
    """Export to ONNX (reference ``onnx_mxnet.export_model``).

    ``sym`` is a Symbol (with ``params`` mapping var name → NDArray) or
    an initialized HybridBlock (traced here; ``params`` ignored).
    ``input_shapes``: dict name → shape, or list in input order.
    """
    from ...gluon.block import HybridBlock
    import mxtpu.symbol as sym_mod

    if isinstance(sym, HybridBlock):
        block = sym
        n_in = len(input_shapes) if input_shapes is not None and \
            not isinstance(input_shapes, dict) else 1
        names = ["data"] if n_in == 1 else [f"data{i}" for i in range(n_in)]
        if isinstance(input_shapes, dict):
            names = list(input_shapes)
        try:
            out = block._trace_symbol(*[sym_mod.var(n) for n in names])
        except TypeError as e:
            # only convert GENUINE arity mismatches (forward takes a
            # different input count than we guessed — the default guess
            # is one 'data' var), determined from the hybrid_forward
            # signature, not by sniffing the message; a TypeError from
            # inside the model body propagates untouched
            import inspect
            try:
                sig = inspect.signature(type(block).hybrid_forward)
                data_args = [
                    p.name for p in list(sig.parameters.values())[2:]
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)
                    and p.default is p.empty
                    and p.name not in block._reg_params]
            except (TypeError, ValueError):
                data_args = None
            if data_args is None or len(data_args) == len(names):
                raise
            raise ValueError(
                f"export_model: {type(block).__name__}.hybrid_forward "
                f"takes {len(data_args)} data input(s) {data_args} but "
                f"{len(names)} were guessed ({names}); pass "
                f"input_shapes as a dict {{name: shape}} or a list "
                f"with one shape per forward input") from e
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        sym = out
        aux_names = set(sym.list_auxiliary_states())
        params = {p.name: p.data() for p in block.collect_params().values()
                  if p.name in aux_names or p.name in sym.list_arguments()}
    params = params or {}
    shapes = _normalize_shapes(sym, params, input_shapes)
    model = export_graph(sym, params, input_shapes=shapes, opset=opset)
    with open(onnx_file, "wb") as f:
        f.write(model.SerializeToString())
    if verbose:
        print(f"exported {len(model.graph.node)} nodes / "
              f"{len(model.graph.initializer)} initializers → {onnx_file}")
    return onnx_file


def import_model(model_file: str):
    """ONNX file → (sym, arg_params, aux_params) (reference
    ``onnx_mxnet.import_model``)."""
    model = onnx_pb2.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    sym, arg_params, aux_params, _ = import_graph(model)
    return sym, arg_params, aux_params


def import_to_gluon(model_file: str, ctx=None):
    """ONNX file → runnable Gluon ``SymbolBlock`` (reference
    ``onnx_mxnet.import_to_gluon``)."""
    import mxtpu.symbol as sym_mod
    from ...gluon.block import SymbolBlock

    model = onnx_pb2.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    sym, arg_params, aux_params, input_names = import_graph(model)
    params = dict(arg_params)
    params.update(aux_params)
    block = SymbolBlock(sym, [sym_mod.var(n) for n in input_names],
                        params=params)
    return block


def get_model_metadata(model_file: str) -> Dict[str, Any]:
    """Input/output names and shapes of an ONNX file (reference
    ``onnx_mxnet.get_model_metadata``)."""
    model = onnx_pb2.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def vi_shape(vi):
        tt = vi.type.tensor_type
        return tuple(d.dim_value if d.WhichOneof("value") == "dim_value"
                     else d.dim_param for d in tt.shape.dim)

    return {
        "input_tensor_data": [(vi.name, vi_shape(vi)) for vi in g.input
                              if vi.name not in inits],
        "output_tensor_data": [(vi.name, vi_shape(vi)) for vi in g.output],
    }
