"""mxtpu → ONNX graph exporter (the mx2onnx direction).

Rebuild of the reference's ``python/mxnet/contrib/onnx/mx2onnx``
[path cite — unverified]: walk the Symbol DAG in topological order and
emit one or more ONNX ``NodeProto`` per mxtpu op through a converter
registry, with parameters becoming graph initializers.

Design notes (TPU-first consequences):
- The Symbol graph here is already framework-neutral — op nodes with
  python-value attrs — so conversion is a name/attr mapping, not a
  trace. Shapes/dtypes come from the symbol's abstract evaluation
  (``Symbol._infer_structs``, i.e. ``jax.eval_shape`` — no kernels run
  and nothing touches a device during export).
- Export is inference-oriented (like the reference exporter): BatchNorm
  uses its running stats, Dropout is the identity.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ...base import MXNetError
from . import onnx_pb2 as _pb

# dtype name ↔ TensorProto.DataType
_NP2ONNX = {
    "float32": _pb.TensorProto.FLOAT,
    "float64": _pb.TensorProto.DOUBLE,
    "float16": _pb.TensorProto.FLOAT16,
    "bfloat16": _pb.TensorProto.BFLOAT16,
    "uint8": _pb.TensorProto.UINT8,
    "int8": _pb.TensorProto.INT8,
    "int16": _pb.TensorProto.INT16,
    "uint16": _pb.TensorProto.UINT16,
    "int32": _pb.TensorProto.INT32,
    "int64": _pb.TensorProto.INT64,
    "uint32": _pb.TensorProto.UINT32,
    "uint64": _pb.TensorProto.UINT64,
    "bool": _pb.TensorProto.BOOL,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def np_dtype_to_onnx(dt) -> int:
    name = _np.dtype(dt).name if str(dt) != "bfloat16" else "bfloat16"
    try:
        return _NP2ONNX[name]
    except KeyError:
        raise ValueError(f"dtype {dt!r} has no ONNX TensorProto mapping")


def make_tensor(name: str, arr: _np.ndarray) -> _pb.TensorProto:
    """numpy → TensorProto with raw_data payload (little-endian, the ONNX
    raw encoding). bfloat16 is stored as its raw 2-byte payload."""
    t = _pb.TensorProto()
    t.name = name
    t.dims.extend(int(d) for d in arr.shape)
    if str(arr.dtype) == "bfloat16":
        t.data_type = _pb.TensorProto.BFLOAT16
        t.raw_data = arr.tobytes()
        return t
    t.data_type = np_dtype_to_onnx(arr.dtype)
    a = _np.ascontiguousarray(arr)
    if a.dtype.byteorder == ">":
        a = a.byteswap().view(a.dtype.newbyteorder("<"))
    t.raw_data = a.tobytes()
    return t


def tensor_to_np(t: _pb.TensorProto) -> _np.ndarray:
    """TensorProto → numpy, accepting both raw_data and the typed
    repeated fields (both appear in the wild)."""
    shape = tuple(t.dims)
    if t.data_type == _pb.TensorProto.BFLOAT16:
        try:
            import ml_dtypes
            dt = _np.dtype(ml_dtypes.bfloat16)
        except ImportError:  # pragma: no cover
            raise ValueError("bfloat16 tensor requires ml_dtypes")
        if t.raw_data:
            return _np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
        # int32_data carries the raw 16-bit payloads per the ONNX spec
        u16 = _np.asarray(t.int32_data, dtype=_np.uint16)
        return u16.view(dt).reshape(shape).copy()
    np_dt = _np.dtype(_ONNX2NP[t.data_type])
    if t.raw_data:
        return _np.frombuffer(t.raw_data, dtype=np_dt).reshape(shape).copy()
    if t.data_type == _pb.TensorProto.FLOAT16:
        # typed storage carries fp16 BIT PATTERNS in int32_data (spec),
        # not numeric values — bitcast, don't convert
        u16 = _np.asarray(t.int32_data, dtype=_np.uint16)
        return u16.view(_np.float16).reshape(shape).copy()
    if t.data_type == _pb.TensorProto.FLOAT:
        data = t.float_data
    elif t.data_type == _pb.TensorProto.DOUBLE:
        data = t.double_data
    elif t.data_type == _pb.TensorProto.INT64:
        data = t.int64_data
    elif t.data_type in (_pb.TensorProto.UINT32, _pb.TensorProto.UINT64):
        data = t.uint64_data  # spec: uint32 values also ride uint64_data
    else:  # int32 field carries every narrower integer/bool/fp16 type
        data = t.int32_data
    return _np.asarray(data, dtype=np_dt).reshape(shape)


class GraphBuilder:
    """Accumulates ONNX graph pieces while the symbol topo-walk runs."""

    def __init__(self, opset: int = 13):
        self.opset = opset
        self.nodes: List[_pb.NodeProto] = []
        self.initializers: List[_pb.TensorProto] = []
        self.inputs: List[_pb.ValueInfoProto] = []
        self.outputs: List[_pb.ValueInfoProto] = []
        self._names_used: set = set()
        self._struct_of: Dict[str, Any] = {}  # value name → ShapeDtypeStruct
        # constant values known at export time (params + folded nodes);
        # materialized as initializers lazily, only when referenced by an
        # emitted node or a graph output
        self.const_np: Dict[str, _np.ndarray] = {}
        self.zero_states: set = set()  # _rnn_init_state outputs

    # -- naming ---------------------------------------------------------
    def unique(self, hint: str) -> str:
        name, i = hint, 0
        while name in self._names_used:
            i += 1
            name = f"{hint}_{i}"
        self._names_used.add(name)
        return name

    # -- emission -------------------------------------------------------
    def add_node(self, op_type: str, inputs: Sequence[str],
                 outputs: Sequence[str], name: Optional[str] = None,
                 **attrs) -> _pb.NodeProto:
        n = _pb.NodeProto()
        n.op_type = op_type
        n.input.extend(inputs)
        n.output.extend(outputs)
        n.name = name or self.unique(op_type.lower())
        for k, v in attrs.items():
            n.attribute.append(self._attr(k, v))
        self.nodes.append(n)
        for o in outputs:
            self._names_used.add(o)
        return n

    @staticmethod
    def _attr(name: str, v) -> _pb.AttributeProto:
        a = _pb.AttributeProto()
        a.name = name
        if isinstance(v, bool):
            a.type = _pb.AttributeProto.INT
            a.i = int(v)
        elif isinstance(v, (int, _np.integer)):
            a.type = _pb.AttributeProto.INT
            a.i = int(v)
        elif isinstance(v, (float, _np.floating)):
            a.type = _pb.AttributeProto.FLOAT
            a.f = float(v)
        elif isinstance(v, str):
            a.type = _pb.AttributeProto.STRING
            a.s = v.encode()
        elif isinstance(v, (list, tuple)):
            if all(isinstance(x, (int, _np.integer)) for x in v):
                a.type = _pb.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            elif all(isinstance(x, (int, float, _np.floating)) for x in v):
                a.type = _pb.AttributeProto.FLOATS
                a.floats.extend(float(x) for x in v)
            else:
                raise ValueError(f"attr {name}: unsupported list {v!r}")
        elif isinstance(v, _pb.TensorProto):
            a.type = _pb.AttributeProto.TENSOR
            a.t.CopyFrom(v)
        else:
            raise ValueError(f"attr {name}: unsupported value {v!r}")
        return a

    def add_initializer(self, hint: str, arr: _np.ndarray) -> str:
        name = self.unique(hint)
        self.initializers.append(make_tensor(name, _np.asarray(arr)))
        return name

    def const_like(self, hint: str, value, ref: str) -> str:
        """Scalar constant initializer matching `ref`'s inferred dtype
        (falls back to f32 when the dtype is unknown)."""
        st = self._struct_of.get(ref)
        dt = _np.dtype(st.dtype) if st is not None else _np.float32
        return self.add_initializer(hint, _np.asarray(value, dtype=dt))

    def i64(self, hint: str, values) -> str:
        return self.add_initializer(
            hint, _np.asarray(list(values), dtype=_np.int64))

    def dtype_of(self, value_name: str):
        st = self._struct_of.get(value_name)
        return _np.dtype(st.dtype) if st is not None else None

    def shape_of(self, value_name: str):
        st = self._struct_of.get(value_name)
        return tuple(st.shape) if st is not None else None

    @staticmethod
    def value_info(name: str, struct) -> _pb.ValueInfoProto:
        vi = _pb.ValueInfoProto()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = np_dtype_to_onnx(struct.dtype)
        for d in struct.shape:
            tt.shape.dim.add().dim_value = int(d)
        return vi


# -- converter registry ------------------------------------------------------
_CONVERTERS: Dict[str, Callable] = {}


def converts(*op_names):
    def deco(fn):
        for n in op_names:
            _CONVERTERS[n] = fn
        return fn
    return deco


def _spatial(attr, nd, default=1):
    if attr is None:
        return [default] * nd
    return [int(x) for x in attr]


def _sym_pads(pad: Sequence[int]) -> List[int]:
    # mxtpu symmetric pad → ONNX [begin..., end...] order
    return list(pad) + list(pad)


@converts("Convolution")
def _conv(b: GraphBuilder, node, ins, outs):
    k = [int(x) for x in node.attrs["kernel"]]
    nd = len(k)
    b.add_node(
        "Conv", ins, outs, name=node.name,
        kernel_shape=k,
        strides=_spatial(node.attrs.get("stride"), nd),
        dilations=_spatial(node.attrs.get("dilate"), nd),
        pads=_sym_pads(_spatial(node.attrs.get("pad"), nd, 0)),
        group=int(node.attrs.get("num_group", 1)))


@converts("Deconvolution")
def _deconv(b, node, ins, outs):
    k = [int(x) for x in node.attrs["kernel"]]
    nd = len(k)
    b.add_node(
        "ConvTranspose", ins, outs, name=node.name,
        kernel_shape=k,
        strides=_spatial(node.attrs.get("stride"), nd),
        dilations=_spatial(node.attrs.get("dilate"), nd),
        pads=_sym_pads(_spatial(node.attrs.get("pad"), nd, 0)),
        output_padding=_spatial(node.attrs.get("adj"), nd, 0),
        group=int(node.attrs.get("num_group", 1)))


@converts("FullyConnected")
def _fc(b, node, ins, outs):
    data = ins[0]
    no_bias = node.attrs.get("no_bias", False) or len(ins) < 3
    shp = b.shape_of(data)
    if node.attrs.get("flatten", True):
        if shp is None or len(shp) != 2:
            flat = b.unique(node.name + "_flat")
            b.add_node("Flatten", [data], [flat], axis=1)
            data = flat
    elif shp is None or len(shp) != 2:
        # ONNX Gemm is 2-D only; N-D flatten=False lowers to
        # MatMul(data, Wᵀ) (+ Add bias), which broadcasts over batch dims
        wt = b.unique(node.name + "_wt")
        b.add_node("Transpose", [ins[1]], [wt], perm=[1, 0])
        mm_out = outs if no_bias else [b.unique(node.name + "_mm")]
        b.add_node("MatMul", [data, wt], mm_out,
                   name=None if no_bias else node.name + "_matmul")
        if not no_bias:
            b.add_node("Add", [mm_out[0], ins[2]], outs, name=node.name)
        return
    gemm_in = [data, ins[1]] + ([] if no_bias else [ins[2]])
    b.add_node("Gemm", gemm_in, outs, name=node.name,
               alpha=1.0, beta=1.0, transA=0, transB=1)


_ACT2ONNX = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}


@converts("Activation")
def _act(b, node, ins, outs):
    b.add_node(_ACT2ONNX[node.attrs.get("act_type", "relu")],
               ins, outs, name=node.name)


@converts("LeakyReLU")
def _leaky(b, node, ins, outs):
    at = node.attrs.get("act_type", "leaky")
    slope = float(node.attrs.get("slope", 0.25))
    if at in ("leaky", "rrelu"):
        b.add_node("LeakyRelu", ins[:1], outs, name=node.name, alpha=slope)
    elif at == "elu":
        b.add_node("Elu", ins[:1], outs, name=node.name, alpha=slope)
    elif at == "selu":
        b.add_node("Selu", ins[:1], outs, name=node.name)
    elif at == "prelu":
        b.add_node("PRelu", ins, outs, name=node.name)
    elif at == "gelu":
        # exact gelu: x * 0.5 * (1 + erf(x / sqrt(2)))
        x = ins[0]
        d = b.unique(node.name + "_div")
        e = b.unique(node.name + "_erf")
        p = b.unique(node.name + "_p1")
        h = b.unique(node.name + "_half")
        b.add_node("Div", [x, b.const_like("sqrt2", _np.sqrt(2.0), x)], [d])
        b.add_node("Erf", [d], [e])
        b.add_node("Add", [e, b.const_like("one", 1.0, x)], [p])
        b.add_node("Mul", [x, p], [h])
        b.add_node("Mul", [h, b.const_like("half", 0.5, x)], outs,
                   name=node.name)
    else:
        raise ValueError(f"LeakyReLU act_type {at!r} not exportable")


@converts("softmax")
def _softmax(b, node, ins, outs):
    if node.attrs.get("temperature") not in (None, 1.0):
        raise ValueError("softmax with temperature is not exportable")
    b.add_node("Softmax", ins[:1], outs, name=node.name,
               axis=int(node.attrs.get("axis", -1)))


@converts("log_softmax")
def _log_softmax(b, node, ins, outs):
    b.add_node("LogSoftmax", ins[:1], outs, name=node.name,
               axis=int(node.attrs.get("axis", -1)))


@converts("SoftmaxOutput")
def _softmax_output(b, node, ins, outs):
    # inference semantics of the training head: softmax over the data input
    b.add_node("Softmax", ins[:1], outs, name=node.name, axis=-1)


@converts("Pooling")
def _pooling(b, node, ins, outs):
    pt = node.attrs.get("pool_type", "max")
    if node.attrs.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(pt)
        if op is None:
            # global sum-pool: ReduceSum over spatial axes
            shp = b.shape_of(ins[0])
            nd = (len(shp) - 2) if shp else 2
            b.add_node("ReduceSum",
                       [ins[0], b.i64(node.name + "_axes",
                                      range(2, 2 + nd))],
                       outs, name=node.name, keepdims=1)
            return
        b.add_node(op, ins, outs, name=node.name)
        return
    k = [int(x) for x in node.attrs["kernel"]]
    nd = len(k)
    stride = node.attrs.get("stride")
    kw = dict(
        kernel_shape=k,
        strides=k if stride is None else _spatial(stride, nd),
        pads=_sym_pads(_spatial(node.attrs.get("pad"), nd, 0)),
        ceil_mode=int(node.attrs.get("pooling_convention", "valid") == "full"))
    if pt == "max":
        b.add_node("MaxPool", ins, outs, name=node.name, **kw)
    elif pt == "avg":
        kw["count_include_pad"] = int(node.attrs.get("count_include_pad",
                                                     True))
        b.add_node("AveragePool", ins, outs, name=node.name, **kw)
    else:
        raise ValueError(f"pool_type {pt!r} not exportable")


@converts("BatchNorm")
def _batchnorm(b, node, ins, outs):
    if int(node.attrs.get("axis", 1)) != 1:
        raise ValueError("BatchNorm(axis != 1) not exportable — ONNX "
                         "BatchNormalization is defined over axis 1 only")
    if node.attrs.get("fix_gamma", False):
        # reference semantic: gamma is pinned to 1 regardless of its
        # stored value. Emit a FRESH ones initializer for THIS node —
        # rewriting the original tensor would also change any other
        # consumer of the same value.
        shp = b.shape_of(ins[1])
        if shp is None:
            raise ValueError(
                f"BatchNorm(fix_gamma=True) export needs gamma's shape "
                f"({node.name})")
        dt = b.dtype_of(ins[1]) or _np.dtype(_np.float32)
        ins = list(ins)
        ins[1] = b.add_initializer(node.name + "_fixed_gamma",
                                   _np.ones(shp, dtype=dt))
    b.add_node("BatchNormalization", ins, outs, name=node.name,
               epsilon=float(node.attrs.get("eps", 1e-5)),
               momentum=float(node.attrs.get("momentum", 0.9)))


@converts("LayerNorm")
def _layernorm(b, node, ins, outs):
    if node.attrs.get("output_mean_var"):
        raise ValueError("LayerNorm(output_mean_var=True) not exportable")
    b.opset = max(b.opset, 17)  # LayerNormalization standardized in 17
    b.add_node("LayerNormalization", ins, outs, name=node.name,
               axis=int(node.attrs.get("axis", -1)),
               epsilon=float(node.attrs.get("eps", 1e-5)))


@converts("LRN")
def _lrn(b, node, ins, outs):
    b.add_node("LRN", ins, outs, name=node.name,
               alpha=float(node.attrs.get("alpha", 1e-4)),
               beta=float(node.attrs.get("beta", 0.75)),
               bias=float(node.attrs.get("knorm", 2.0)),
               size=int(node.attrs["nsize"]))


@converts("Dropout")
def _dropout(b, node, ins, outs):
    # ONNX Dropout defaults to inference (identity) when training_mode
    # is absent; ratio rides along for consumers that re-train.
    b.add_node("Dropout", ins[:1], outs, name=node.name)


@converts("Embedding")
def _embedding(b, node, ins, outs):
    idx = b.unique(node.name + "_idx")
    b.add_node("Cast", [ins[0]], [idx], to=int(_pb.TensorProto.INT64))
    b.add_node("Gather", [ins[1], idx], outs, name=node.name, axis=0)


@converts("take")
def _take(b, node, ins, outs):
    idx = b.unique(node.name + "_idx")
    b.add_node("Cast", [ins[1]], [idx], to=int(_pb.TensorProto.INT64))
    b.add_node("Gather", [ins[0], idx], outs, name=node.name,
               axis=int(node.attrs.get("axis", 0)))


# -- elementwise binary ------------------------------------------------------
_BINOP = {"broadcast_add": "Add", "elemwise_add": "Add", "add": "Add",
          "broadcast_sub": "Sub", "elemwise_sub": "Sub",
          "broadcast_mul": "Mul", "elemwise_mul": "Mul",
          "broadcast_div": "Div", "elemwise_div": "Div",
          "broadcast_power": "Pow",
          "broadcast_maximum": "Max", "broadcast_minimum": "Min",
          "maximum": "Max", "minimum": "Min"}


def _binop(b, node, ins, outs):
    b.add_node(_BINOP[node.op], ins, outs, name=node.name)


for _name in _BINOP:
    _CONVERTERS[_name] = _binop

_CMPOP = {"broadcast_equal": "Equal", "broadcast_not_equal": "Equal",
          "broadcast_greater": "Greater", "broadcast_lesser": "Less",
          "broadcast_greater_equal": "GreaterOrEqual",
          "broadcast_lesser_equal": "LessOrEqual"}


def _cmpop(b, node, ins, outs):
    raw = b.unique(node.name + "_bool")
    b.add_node(_CMPOP[node.op], ins, [raw])
    cur = raw
    if node.op == "broadcast_not_equal":
        nn = b.unique(node.name + "_not")
        b.add_node("Not", [cur], [nn])
        cur = nn
    # mxtpu comparisons return 0/1 in the operand dtype, ONNX returns bool
    dt = b.dtype_of(ins[0]) or _np.dtype(_np.float32)
    b.add_node("Cast", [cur], outs, name=node.name,
               to=int(np_dtype_to_onnx(dt)))


for _name in _CMPOP:
    _CONVERTERS[_name] = _cmpop

# -- scalar ops --------------------------------------------------------------
_SCALAR = {"_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
           "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
           "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
           "_power_scalar": ("Pow", False), "_rpower_scalar": ("Pow", True),
           "_maximum_scalar": ("Max", False), "_minimum_scalar": ("Min", False),
           "_mod_scalar": ("Mod", False)}


def _scalar_op(b, node, ins, outs):
    op, rev = _SCALAR[node.op]
    sc = node.attrs.get("scalar", 0.0)
    # the scalar const takes the NODE OUTPUT dtype (what jnp's promotion
    # produced natively — e.g. int32 / 2 → float32); when that differs
    # from the input dtype, cast the input so the ONNX binary op sees
    # matching operand types and reproduces the native numerics
    out_dt = b.dtype_of(node.name)
    in_dt = b.dtype_of(ins[0])
    x = ins[0]
    if out_dt is not None and in_dt is not None and out_dt != in_dt:
        cast_in = b.unique(node.name + "_castin")
        b.add_node("Cast", [x], [cast_in], to=int(np_dtype_to_onnx(out_dt)))
        x = cast_in
    dt = out_dt or in_dt or _np.dtype(_np.float32)
    c = b.add_initializer(node.name + "_scalar", _np.asarray(sc, dtype=dt))
    lhs, rhs = (c, x) if rev else (x, c)
    if op == "Mod" and dt.kind == "f":
        # jnp.mod is floor-mod; ONNX float Mod must be fmod=1 (C fmod),
        # which differs on negatives — decompose: a - floor(a/b)*b
        d = b.unique(node.name + "_div")
        fl = b.unique(node.name + "_floor")
        mu = b.unique(node.name + "_mul")
        b.add_node("Div", [lhs, rhs], [d])
        b.add_node("Floor", [d], [fl])
        b.add_node("Mul", [fl, rhs], [mu])
        b.add_node("Sub", [lhs, mu], outs, name=node.name)
        return
    b.add_node(op, [lhs, rhs], outs, name=node.name)


for _name in _SCALAR:
    _CONVERTERS[_name] = _scalar_op

# -- unary -------------------------------------------------------------------
_UNARY = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "negative": "Neg",
          "abs": "Abs", "erf": "Erf", "floor": "Floor", "ceil": "Ceil",
          "round": "Round", "sign": "Sign", "sin": "Sin", "cos": "Cos",
          "identity": "Identity", "BlockGrad": "Identity",
          "stop_gradient": "Identity", "reciprocal": "Reciprocal"}


def _unary(b, node, ins, outs):
    b.add_node(_UNARY[node.op], ins[:1], outs, name=node.name)


for _name in _UNARY:
    _CONVERTERS[_name] = _unary


@converts("square")
def _square(b, node, ins, outs):
    b.add_node("Mul", [ins[0], ins[0]], outs, name=node.name)


@converts("rsqrt")
def _rsqrt(b, node, ins, outs):
    s = b.unique(node.name + "_sqrt")
    b.add_node("Sqrt", ins[:1], [s])
    b.add_node("Reciprocal", [s], outs, name=node.name)


# -- shape ops ---------------------------------------------------------------
@converts("reshape")
def _reshape(b, node, ins, outs):
    if node.attrs.get("reverse"):
        raise ValueError("reshape(reverse=True) not exportable")
    shape = [int(x) for x in node.attrs["shape"]]
    if any(s in (-2, -3, -4) for s in shape):
        # resolve MXNet special codes against the inferred output shape
        shp = b.shape_of(node.name)
        if shp is None:
            raise ValueError(f"reshape special codes need inferred shapes "
                             f"({node.name})")
        shape = [int(x) for x in shp]
    b.add_node("Reshape", [ins[0], b.i64(node.name + "_shape", shape)],
               outs, name=node.name)


@converts("Flatten", "flatten")
def _flatten(b, node, ins, outs):
    b.add_node("Flatten", ins, outs, name=node.name, axis=1)


@converts("reshape_like")
def _reshape_like(b, node, ins, outs):
    shp = b.shape_of(ins[1]) or b.shape_of(node.name)
    if shp is None:
        raise ValueError("reshape_like export needs inferred shapes")
    b.add_node("Reshape",
               [ins[0], b.i64(node.name + "_shape",
                              [int(x) for x in shp])],
               outs, name=node.name)


@converts("transpose")
def _transpose(b, node, ins, outs):
    axes = node.attrs.get("axes")
    kw = {"perm": [int(a) for a in axes]} if axes else {}
    b.add_node("Transpose", ins, outs, name=node.name, **kw)


@converts("swapaxes")
def _swapaxes(b, node, ins, outs):
    shp = b.shape_of(ins[0])
    if shp is None:
        raise ValueError("swapaxes export needs inferred input shape")
    perm = list(range(len(shp)))
    d1, d2 = int(node.attrs.get("dim1", 0)), int(node.attrs.get("dim2", 0))
    perm[d1], perm[d2] = perm[d2], perm[d1]
    b.add_node("Transpose", ins, outs, name=node.name, perm=perm)


@converts("expand_dims")
def _expand_dims(b, node, ins, outs):
    b.add_node("Unsqueeze",
               [ins[0], b.i64(node.name + "_axes",
                              [int(node.attrs["axis"])])],
               outs, name=node.name)


@converts("squeeze")
def _squeeze(b, node, ins, outs):
    ax = node.attrs.get("axis")
    inputs = [ins[0]]
    if ax is not None:
        axes = [ax] if isinstance(ax, int) else list(ax)
        inputs.append(b.i64(node.name + "_axes", [int(a) for a in axes]))
    b.add_node("Squeeze", inputs, outs, name=node.name)


@converts("concat")
def _concat(b, node, ins, outs):
    b.add_node("Concat", ins, outs, name=node.name,
               axis=int(node.attrs.get("dim", 1)))


@converts("stack")
def _stack(b, node, ins, outs):
    axis = int(node.attrs.get("axis", 0))
    axes = b.i64(node.name + "_axes", [axis])
    unsq = []
    for i, x in enumerate(ins):
        u = b.unique(f"{node.name}_u{i}")
        b.add_node("Unsqueeze", [x, axes], [u])
        unsq.append(u)
    b.add_node("Concat", unsq, outs, name=node.name, axis=axis)


@converts("split")
def _split(b, node, ins, outs):
    axis = int(node.attrs.get("axis", 1))
    if node.attrs.get("squeeze_axis"):
        raw = [b.unique(f"{node.name}_p{i}") for i in range(len(outs))]
        b.add_node("Split", ins, raw, name=node.name, axis=axis)
        axes = b.i64(node.name + "_axes", [axis])
        for r, o in zip(raw, outs):
            b.add_node("Squeeze", [r, axes], [o])
    else:
        b.add_node("Split", ins, outs, name=node.name, axis=axis)


@converts("slice")
def _slice(b, node, ins, outs):
    begin = [0 if x is None else int(x) for x in node.attrs["begin"]]
    end = [2 ** 62 if e is None else int(e) for e in node.attrs["end"]]
    step = node.attrs.get("step")
    if step and any(s is not None and int(s) < 0 for s in step):
        # the open-end sentinel below is wrong under reversed traversal
        raise ValueError("slice with negative step is not exportable")
    inputs = [ins[0],
              b.i64(node.name + "_starts", begin),
              b.i64(node.name + "_ends", end),
              b.i64(node.name + "_axes", range(len(begin)))]
    if step:
        inputs.append(b.i64(node.name + "_steps",
                            [1 if s is None else int(s) for s in step]))
    b.add_node("Slice", inputs, outs, name=node.name)


@converts("slice_axis")
def _slice_axis(b, node, ins, outs):
    axis = int(node.attrs["axis"])
    begin = int(node.attrs["begin"])
    end = node.attrs.get("end")
    b.add_node("Slice",
               [ins[0],
                b.i64(node.name + "_starts", [begin]),
                b.i64(node.name + "_ends",
                      [2 ** 62 if end is None else int(end)]),
                b.i64(node.name + "_axes", [axis])],
               outs, name=node.name)


@converts("clip")
def _clip(b, node, ins, outs):
    # ONNX Clip takes optional min/max inputs; an absent bound is an
    # empty-string placeholder, NOT a materialized ±inf (which would
    # overflow integer dtypes)
    inputs = [ins[0]]
    a_min, a_max = node.attrs.get("a_min"), node.attrs.get("a_max")
    inputs.append("" if a_min is None
                  else b.const_like(node.name + "_min", a_min, ins[0]))
    if a_max is not None:
        inputs.append(b.const_like(node.name + "_max", a_max, ins[0]))
    elif inputs[1] == "":
        inputs = inputs[:1]  # no bounds at all
    b.add_node("Clip", inputs, outs, name=node.name)


@converts("cast")
def _cast(b, node, ins, outs):
    b.add_node("Cast", ins, outs, name=node.name,
               to=int(np_dtype_to_onnx(node.attrs["dtype"])))


@converts("pad")
def _pad(b, node, ins, outs):
    pw = [int(x) for x in node.attrs["pad_width"]]
    nd = len(pw) // 2
    onnx_pads = [pw[2 * i] for i in range(nd)] + \
                [pw[2 * i + 1] for i in range(nd)]
    mode = node.attrs.get("mode", "constant")
    inputs = [ins[0], b.i64(node.name + "_pads", onnx_pads)]
    if mode == "constant":
        inputs.append(b.const_like(node.name + "_cval",
                                   node.attrs.get("constant_value", 0),
                                   ins[0]))
    b.add_node("Pad", inputs, outs, name=node.name,
               mode={"constant": "constant", "edge": "edge",
                     "reflect": "reflect"}[mode])


@converts("where")
def _where(b, node, ins, outs):
    cond = b.unique(node.name + "_cond")
    b.add_node("Cast", [ins[0]], [cond], to=int(_pb.TensorProto.BOOL))
    b.add_node("Where", [cond, ins[1], ins[2]], outs, name=node.name)


@converts("add_n")
def _add_n(b, node, ins, outs):
    b.add_node("Sum", ins, outs, name=node.name)


# -- reductions --------------------------------------------------------------
_REDUCE = {"mean": "ReduceMean", "max": "ReduceMax", "min": "ReduceMin",
           "prod": "ReduceProd"}


def _reduce_axes(b, node, ins):
    """Resolve the mxtpu axis/exclude attrs to explicit ONNX axes
    (None = reduce all)."""
    ax = node.attrs.get("axis")
    if ax is None:
        return None  # reduce all (exclude has no effect without axis)
    axes = [int(ax)] if isinstance(ax, int) else [int(a) for a in ax]
    if node.attrs.get("exclude"):
        shp = b.shape_of(ins[0])
        if shp is None:
            raise ValueError(
                f"{node.op}(exclude=True) export needs inferred shapes")
        nd_ = len(shp)
        listed = {a % nd_ for a in axes}
        axes = [i for i in range(nd_) if i not in listed]
    return axes


def _reduce(b, node, ins, outs):
    axes = _reduce_axes(b, node, ins)
    kw = {"keepdims": int(bool(node.attrs.get("keepdims", False)))}
    if axes is not None:
        kw["axes"] = axes
    b.add_node(_REDUCE[node.op], ins[:1], outs, name=node.name, **kw)


for _name in _REDUCE:
    _CONVERTERS[_name] = _reduce


@converts("sum")
def _reduce_sum(b, node, ins, outs):
    # opset 13 moved ReduceSum's axes from attr to input
    axes = _reduce_axes(b, node, ins)
    inputs = [ins[0]]
    if axes is not None:
        inputs.append(b.i64(node.name + "_axes", axes))
    b.add_node("ReduceSum", inputs, outs, name=node.name,
               keepdims=int(bool(node.attrs.get("keepdims", False))))


@converts("dot")
def _dot(b, node, ins, outs):
    a, c = ins
    sa, sc = b.shape_of(a), b.shape_of(c)
    if node.attrs.get("transpose_a"):
        if sa is None or len(sa) != 2:
            raise ValueError("dot(transpose_a) export needs 2-D lhs")
        t = b.unique(node.name + "_at")
        b.add_node("Transpose", [a], [t], perm=[1, 0])
        a = t
    if node.attrs.get("transpose_b"):
        if sc is None or len(sc) != 2:
            raise ValueError("dot(transpose_b) export needs 2-D rhs")
        t = b.unique(node.name + "_bt")
        b.add_node("Transpose", [c], [t], perm=[1, 0])
        c = t
    # MXNet dot contracts lhs-last with rhs-first: MatMul agrees when the
    # rhs is ≤2-D (the overwhelmingly common case)
    if sc is not None and len(sc) > 2:
        raise ValueError("dot with >2-D rhs is not exportable to MatMul")
    b.add_node("MatMul", [a, c], outs, name=node.name)


@converts("RNN")
def _rnn(b, node, ins, outs):
    """Fused RNN → ONNX LSTM/GRU/RNN, one node per layer.

    The cuDNN-packed 1-D parameter vector is unpacked via the op's own
    ``rnn_param_layout`` — constant folding has already collapsed the
    gluon-side reshape/concat packing chain, so ``ins[1]`` is a known
    constant. Gate orders are remapped (ours i,f,g,o / r,z,n → ONNX
    i,o,f,c / z,r,h); GRU exports with ``linear_before_reset=1``, the
    cuDNN semantic this op implements."""
    from ...ndarray.ops import rnn_param_layout, rnn_gates

    mode = node.attrs.get("mode", "lstm").lower()
    if node.attrs.get("projection_size") is not None:
        raise ValueError("RNN projection_size not exportable")
    if node.attrs.get("lstm_state_clip_min") is not None or \
            node.attrs.get("lstm_state_clip_max") is not None:
        raise ValueError("RNN state clipping not exportable")
    L = int(node.attrs.get("num_layers", 1))
    bi = bool(node.attrs.get("bidirectional", False))
    H = int(node.attrs["state_size"])
    d = 2 if bi else 1
    is_lstm = mode == "lstm"
    pvec = b.const_np.get(ins[1])
    if pvec is None:
        raise ValueError("RNN export needs compile-time-constant "
                         "parameters (an initializer or foldable chain)")
    shp = b.shape_of(ins[0])
    if shp is None:
        raise ValueError("RNN export needs the inferred input shape")
    T, N, C = (int(x) for x in shp)
    ng = rnn_gates(mode)
    layout, total = rnn_param_layout(mode, C, H, L, bi)
    pvec = _np.asarray(pvec).reshape(-1)
    if pvec.shape[0] != total:
        raise ValueError(f"RNN parameters size {pvec.shape[0]} != "
                         f"expected {total}")

    def get(kind, layer, dr):
        off, shape = layout[(kind, layer, dr)]
        n = int(_np.prod(shape))
        return pvec[off:off + n].reshape(shape)

    def reorder(w):  # rows grouped per gate, our order → ONNX order
        if mode == "lstm":  # i,f,g,o → i,o,f,c(=g)
            i, f, g, o = _np.split(w, 4, axis=0)
            return _np.concatenate([i, o, f, g], axis=0)
        if mode == "gru":  # r,z,n → z,r,h(=n)
            r, z, n_ = _np.split(w, 3, axis=0)
            return _np.concatenate([z, r, n_], axis=0)
        return w

    onnx_op = {"lstm": "LSTM", "gru": "GRU",
               "rnn_tanh": "RNN", "rnn_relu": "RNN"}[mode]
    h0_given = ins[2] not in b.zero_states
    c0_given = is_lstm and len(ins) > 3 and ins[3] not in b.zero_states

    def layer_state(src, layer, hint):
        if L == 1:
            return src
        sl = b.unique(f"{node.name}_{hint}{layer}")
        b.add_node("Slice",
                   [src, b.i64(f"{sl}_starts", [layer * d]),
                    b.i64(f"{sl}_ends", [(layer + 1) * d]),
                    b.i64(f"{sl}_axes", [0])], [sl])
        return sl

    cur = ins[0]
    hts, cts = [], []
    for layer in range(L):
        W = _np.stack([reorder(get("i2h_weight", layer, dr))
                       for dr in range(d)])
        R = _np.stack([reorder(get("h2h_weight", layer, dr))
                       for dr in range(d)])
        Bv = _np.stack([_np.concatenate(
            [reorder(get("i2h_bias", layer, dr)[:, None])[:, 0],
             reorder(get("h2h_bias", layer, dr)[:, None])[:, 0]])
            for dr in range(d)])
        inputs = [cur,
                  b.add_initializer(f"{node.name}_W{layer}", W),
                  b.add_initializer(f"{node.name}_R{layer}", R),
                  b.add_initializer(f"{node.name}_B{layer}", Bv),
                  ""]  # sequence_lens absent
        if h0_given:
            inputs.append(layer_state(ins[2], layer, "h0"))
        elif is_lstm and c0_given:
            inputs.append("")
        if is_lstm and c0_given:
            inputs.append(layer_state(ins[3], layer, "c0"))
        while inputs and inputs[-1] == "":
            inputs.pop()
        y = b.unique(f"{node.name}_Y{layer}")
        yh = b.unique(f"{node.name}_Yh{layer}")
        node_outs = [y, yh]
        if is_lstm:
            yc = b.unique(f"{node.name}_Yc{layer}")
            node_outs.append(yc)
            cts.append(yc)
        hts.append(yh)
        kw = dict(hidden_size=H,
                  direction="bidirectional" if bi else "forward")
        if mode == "gru":
            kw["linear_before_reset"] = 1
        if onnx_op == "RNN":
            kw["activations"] = \
                ["Tanh" if mode == "rnn_tanh" else "Relu"] * d
        b.add_node(onnx_op, inputs, node_outs, **kw)
        # Y (T, D, N, H) → (T, N, D*H), the fused-op layout
        tr = b.unique(f"{node.name}_Ytr{layer}")
        b.add_node("Transpose", [y], [tr], perm=[0, 2, 1, 3])
        nxt = outs[0] if layer == L - 1 else \
            b.unique(f"{node.name}_l{layer}")
        b.add_node("Reshape",
                   [tr, b.i64(f"{node.name}_yshape{layer}",
                              [T, N, d * H])], [nxt])
        cur = nxt
    if len(outs) > 1:  # final hidden: per-layer (D,N,H) → (L*D, N, H)
        if len(hts) == 1:
            b.add_node("Identity", [hts[0]], [outs[1]])
        else:
            b.add_node("Concat", hts, [outs[1]], axis=0)
    if len(outs) > 2:
        if len(cts) == 1:
            b.add_node("Identity", [cts[0]], [outs[2]])
        else:
            b.add_node("Concat", cts, [outs[2]], axis=0)


@converts("batch_dot")
def _batch_dot(b, node, ins, outs):
    a, c = ins
    for key, idx in (("transpose_a", 0), ("transpose_b", 1)):
        if node.attrs.get(key):
            shp = b.shape_of(ins[idx])
            if shp is None:
                raise ValueError(f"batch_dot({key}) export needs shapes")
            perm = list(range(len(shp)))
            perm[-1], perm[-2] = perm[-2], perm[-1]
            t = b.unique(f"{node.name}_t{idx}")
            b.add_node("Transpose", [ins[idx]], [t], perm=perm)
            if idx == 0:
                a = t
            else:
                c = t
    b.add_node("MatMul", [a, c], outs, name=node.name)


# -- constant folding --------------------------------------------------------
# never fold: stochastic ops (one folded sample would freeze the
# randomness). _rnn_init_state never reaches here — export_graph
# `continue`s on it before the fold check.
_NO_FOLD_OPS = {"Dropout"}


def _fold_node(b: GraphBuilder, node, ins, outs) -> bool:
    """Constant-fold one op node: when every input value is already
    known at export time (a parameter initializer or an earlier folded
    node), evaluate the op eagerly through the shared op registry and
    record the results in ``b.const_np`` instead of emitting ONNX nodes.

    This is what collapses the RNN converter's parameter-packing chain
    (per-layer reshape/concat of the cuDNN-packed vector) into the
    single constant ``ins[1]`` the converter reads back; folded
    intermediates never reach the file (the lazy initializer
    materialization in export_graph only writes referenced names).
    Returns True when the node was folded (caller skips conversion)."""
    if not ins or any(i not in b.const_np for i in ins):
        return False
    op = node.op
    low = op.lower()
    if op in _NO_FOLD_OPS or "random" in low or "sample" in low or \
            "rand" in low:
        return False
    import jax.numpy as jnp

    from ... import autograd as _autograd
    from ...ndarray import NDArray as _NDArray
    from ...symbol.symbol import _call_registry_op
    try:
        with _autograd.pause():
            in_nds = [_NDArray(jnp.asarray(b.const_np[i])) for i in ins]
            results = _call_registry_op(node, in_nds)
    except Exception:
        return False  # not evaluable eagerly — emit through a converter
    if len(results) < len(outs):
        return False
    import jax
    for o, r in zip(outs, results):
        arr = _np.asarray(r.asnumpy())
        b.const_np[o] = arr
        b._struct_of.setdefault(
            o, jax.ShapeDtypeStruct(arr.shape, arr.dtype))
    return True


# -- graph-level export ------------------------------------------------------
def _onnx_value_names(node) -> List[str]:
    n_out = node.num_outputs or 1
    return [node.name if i == 0 else f"{node.name}_out{i}"
            for i in range(n_out)]


def export_graph(sym, params: Dict[str, Any],
                 input_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                 opset: int = 13,
                 graph_name: str = "mxtpu") -> _pb.ModelProto:
    """Symbol + params → ModelProto. `params` maps var name → NDArray or
    numpy array (becomes an initializer); remaining vars are graph inputs
    whose shapes come from `input_shapes`."""
    import jax

    np_params = {}
    for k, v in params.items():
        np_params[k] = _np.asarray(getattr(v, "asnumpy", lambda: v)())

    nodes = sym._topo()
    b = GraphBuilder(opset=opset)

    # shape/dtype inference over the whole graph (jax.eval_shape — abstract)
    kw = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
          for k, v in np_params.items()}
    for k, v in (input_shapes or {}).items():
        kw.setdefault(k, jax.ShapeDtypeStruct(tuple(v), _np.float32))
    try:
        structs = sym._infer_structs(**kw)
    except MXNetError as e:
        # re-run as the mxlint graph-validity pass (MXL100) so the
        # failure names the first inconsistent node with its op and
        # inferred input shapes, instead of a deep trace-internal error
        from ..analysis.graph import format_issues, validate_graph
        issues = validate_graph(sym, params=np_params,
                                input_shapes=input_shapes)
        detail = format_issues(issues) if issues else str(e)
        raise ValueError(
            f"ONNX export aborted — graph failed validation:\n{detail}"
        ) from e
    entry_structs = {}
    if structs is not None:
        entry_structs, var_structs = structs

    value_names: Dict[Tuple[int, int], str] = {}
    for node in nodes:
        if node.is_var():
            value_names[(id(node), 0)] = node.name
            b._names_used.add(node.name)
            if node.name in np_params:
                arr = np_params[node.name]
                b.const_np[node.name] = arr
                b._struct_of[node.name] = jax.ShapeDtypeStruct(
                    arr.shape, arr.dtype)
            else:
                if structs is not None and node.name in var_structs:
                    st = var_structs[node.name]
                elif input_shapes and node.name in input_shapes:
                    st = jax.ShapeDtypeStruct(
                        tuple(input_shapes[node.name]), _np.float32)
                else:
                    raise ValueError(
                        f"input {node.name!r}: no shape available — pass "
                        f"input_shapes={{'{node.name}': (...)}}")
                b.inputs.append(b.value_info(node.name, st))
                b._struct_of[node.name] = st

    for node in nodes:
        if node.is_var():
            continue
        outs = _onnx_value_names(node)
        for i, o in enumerate(outs):
            value_names[(id(node), i)] = o
            st = entry_structs.get((id(node), i))
            if st is not None:
                b._struct_of[o] = st
        ins = [value_names[(id(p), i)] for p, i in node.inputs]
        if node.op == "_rnn_init_state":
            # a zero initial state — the RNN converter omits the
            # corresponding optional ONNX input (defaults to zeros)
            b.zero_states.update(outs)
            continue
        if _fold_node(b, node, ins, outs):
            continue
        conv = _CONVERTERS.get(node.op)
        if conv is None:
            raise ValueError(
                f"op {node.op!r} ({node.name}) has no ONNX converter; "
                f"supported: {sorted(_CONVERTERS)}")
        conv(b, node, ins, outs)

    # prune nodes whose outputs never reach a graph output (e.g. the
    # state heads a converter emits for a multi-output op whose states
    # the symbol never consumed) — reverse sweep over the topo order
    head_names = {value_names[(id(h), i)] for h, i in sym._entries}
    needed = set(head_names)
    kept: List[_pb.NodeProto] = []
    for n2 in reversed(b.nodes):
        if any(o in needed for o in n2.output):
            kept.append(n2)
            needed.update(i for i in n2.input if i)
    b.nodes = kept[::-1]

    # lazily materialize constants (params + folded values) that emitted
    # nodes or graph outputs actually reference — folding intermediates
    # (e.g. the RNN packing chain) never hit the file
    referenced = set(head_names)
    for n2 in b.nodes:
        referenced.update(n2.input)
    # drop initializers that only pruned nodes consumed
    b.initializers = [t for t in b.initializers if t.name in referenced]
    existing = {t.name for t in b.initializers}
    produced = {o for n2 in b.nodes for o in n2.output}
    bridge = {n for n in head_names
              if n in b.const_np and n not in produced}
    for name in sorted(referenced):
        if name and name not in existing and name in b.const_np and \
                name not in bridge:
            b.initializers.append(
                make_tensor(name, _np.asarray(b.const_np[name])))
            existing.add(name)
    for name in sorted(bridge):
        # a fully-folded graph output: initializers are not valid
        # outputs, so bridge with Identity
        cname = b.unique(name + "_const")
        b.initializers.append(
            make_tensor(cname, _np.asarray(b.const_np[name])))
        b.add_node("Identity", [cname], [name])
        produced.add(name)
    inputs_set = {vi.name for vi in b.inputs}
    for name in sorted(referenced):
        if name and name not in existing and name not in produced and \
                name not in inputs_set:
            raise ValueError(
                f"value {name!r} is consumed but never produced — "
                f"likely an unsupported zero-state or optional output")

    model = _pb.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxtpu"
    model.producer_version = "1.0"
    model.opset_import.add(domain="", version=b.opset)
    g = model.graph
    g.name = graph_name
    g.node.extend(b.nodes)
    g.initializer.extend(b.initializers)
    g.input.extend(b.inputs)
    for head, i in sym._entries:
        name = value_names[(id(head), i)]
        st = b._struct_of.get(name)
        if st is not None:
            g.output.append(b.value_info(name, st))
        else:
            vi = _pb.ValueInfoProto()
            vi.name = name
            g.output.append(vi)
    return model
