"""TensorBoard summaries — the in-tree counterpart of the external
``mxboard`` package the reference ecosystem used (SURVEY §5.5:
"TensorBoard via the external mxboard package (not in-tree)"; the
rebuild ships it in-tree over tensorboardX).

>>> from mxtpu.contrib.summary import SummaryWriter
>>> with SummaryWriter(logdir="./logs") as sw:
...     sw.add_scalar("loss", 0.5, global_step=1)
"""
from __future__ import annotations

import numpy as _onp

from ..ndarray import NDArray

__all__ = ["SummaryWriter"]


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _onp.asarray(x)


class SummaryWriter:
    """mxboard-compatible writer (add_scalar/add_histogram/add_image/
    add_text), NDArray-aware."""

    def __init__(self, logdir: str = "./logs", flush_secs: int = 120,
                 **kwargs):
        from tensorboardX import SummaryWriter as _TBW
        self._w = _TBW(logdir=logdir, flush_secs=flush_secs, **kwargs)

    def add_scalar(self, tag, value, global_step=None):
        v = _np(value).reshape(-1)
        if v.size != 1:
            raise ValueError(
                f"add_scalar needs a scalar, got shape {_np(value).shape}"
                " — use add_histogram for vectors")
        self._w.add_scalar(tag, float(v[0]), global_step)

    def add_histogram(self, tag, values, global_step=None, bins="auto"):
        self._w.add_histogram(tag, _np(values), global_step, bins=bins)

    def add_image(self, tag, image, global_step=None,
                  dataformats="CHW"):
        self._w.add_image(tag, _np(image), global_step,
                          dataformats=dataformats)

    def add_text(self, tag, text, global_step=None):
        self._w.add_text(tag, text, global_step)

    def flush(self):
        self._w.flush()

    def close(self):
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
