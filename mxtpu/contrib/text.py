"""mx.contrib.text (reference ``python/mxnet/contrib/text/`` [path
cite — unverified]): vocabulary + token-embedding containers feeding
``nn.Embedding``. The reference downloaded pretrained GloVe/fastText
tables; this environment has no egress, so pretrained loading reads
local files in the same text format, and ``CustomEmbedding`` covers
user-supplied tables.
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from .. import ndarray as nd

__all__ = ["Vocabulary", "CustomEmbedding", "count_tokens_from_str"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n", to_lower: bool = False,
                          counter_to_update=None):
    """Token frequency counter (reference
    ``text.utils.count_tokens_from_str``)."""
    source = source_str.lower() if to_lower else source_str
    tokens = source.replace(seq_delim, token_delim).split(token_delim)
    tokens = [t for t in tokens if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class Vocabulary:
    """Indexed vocabulary (reference ``text.vocab.Vocabulary``):
    tokens sorted by frequency (ties broken lexically), index 0 is the
    unknown token, optional reserved tokens follow it."""

    def __init__(self, counter=None, most_freq_count: Optional[int] = None,
                 min_freq: int = 1, unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens contains duplicates")
        self._unknown_token = unknown_token
        self._idx_to_token: List[str] = [unknown_token] + reserved_tokens
        self._token_to_idx: Dict[str, int] = {
            t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq:
                    continue
                if tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    def to_indices(self, tokens):
        """Token(s) → index/indices; unknowns map to index 0."""
        if isinstance(tokens, str):
            return self._token_to_idx.get(tokens, 0)
        return [self._token_to_idx.get(t, 0) for t in tokens]

    def to_tokens(self, indices):
        if isinstance(indices, int):
            indices = [indices]
            single = True
        else:
            single = False
        out = []
        for i in indices:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"index {i} out of vocabulary range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out


class CustomEmbedding:
    """Token embedding from a user table or a text file of
    ``token v1 v2 ...`` lines (reference ``text.embedding`` family —
    the file format GloVe/fastText ship)."""

    def __init__(self, file_path: Optional[str] = None,
                 vocabulary: Optional[Vocabulary] = None,
                 tokens: Optional[Sequence[str]] = None,
                 vectors=None, elem_delim: str = " ",
                 init_unknown_vec=None):
        table: Dict[str, onp.ndarray] = {}
        dim = None
        if file_path is not None:
            with open(file_path, encoding="utf-8") as f:
                for lineno, line in enumerate(f):
                    parts = line.rstrip("\n").split(elem_delim)
                    if len(parts) < 2:
                        continue
                    if lineno == 0 and len(parts) == 2:
                        try:             # fastText '<count> <dim>' header
                            int(parts[0]), int(parts[1])
                            continue
                        except ValueError:
                            pass
                    try:
                        vec = onp.asarray([float(x) for x in parts[1:]
                                           if x], onp.float32)
                    except ValueError:
                        continue         # malformed line (token w/ delim)
                    if vec.size == 0:
                        continue
                    if dim is None:
                        dim = vec.size
                    elif vec.size != dim:
                        continue
                    table[parts[0]] = vec
        if tokens is not None:
            vec_np = vectors.asnumpy() if hasattr(vectors, "asnumpy") \
                else onp.asarray(vectors, onp.float32)
            if len(tokens) != vec_np.shape[0]:
                raise MXNetError("tokens/vectors length mismatch")
            dim = vec_np.shape[1]
            for t, v in zip(tokens, vec_np):
                table[t] = onp.asarray(v, onp.float32)
        if dim is None:
            raise MXNetError("no embedding source given")
        self.vec_len = int(dim)
        self._table = table
        self._unk = (init_unknown_vec(dim) if init_unknown_vec
                     else onp.zeros(dim, onp.float32))
        self._vocab = vocabulary
        if vocabulary is not None:
            rows = [self._table.get(t, self._unk)
                    for t in vocabulary.idx_to_token]
            # ONE stored NDArray (reference semantics: in-place writes
            # to idx_to_vec persist; a per-access copy would lose them)
            self._idx_to_vec = nd.array(onp.stack(rows))
        else:
            self._idx_to_vec = None

    @property
    def idx_to_vec(self):
        """(vocab, dim) NDArray aligned to the attached Vocabulary —
        drop into ``nn.Embedding(...).weight.set_data``."""
        if self._idx_to_vec is None:
            raise MXNetError("no Vocabulary attached")
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup: bool = False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        rows = []
        for t in toks:
            v = self._table.get(t)
            if v is None and lower_case_backup:
                v = self._table.get(t.lower())
            rows.append(v if v is not None else self._unk)
        out = nd.array(onp.stack(rows))
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors for tokens known to the table OR the
        attached vocabulary (the reference's main use: initializing
        OOV rows)."""
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vec = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else onp.asarray(new_vectors, onp.float32)
        vec = vec.reshape(len(toks), -1)
        if vec.shape[1] != self.vec_len:
            raise MXNetError(
                f"vector width {vec.shape[1]} != vec_len {self.vec_len}")
        for t in toks:     # validate ALL before mutating ANY state
            if t not in self._table and not (
                    self._vocab is not None
                    and t in self._vocab.token_to_idx):
                raise MXNetError(
                    f"token {t!r} in neither the embedding table nor "
                    "the attached vocabulary")
        for t, v in zip(toks, vec):
            self._table[t] = onp.asarray(v, onp.float32)
            if self._vocab is not None and t in self._vocab.token_to_idx:
                i = self._vocab.token_to_idx[t]
                self._idx_to_vec[i] = nd.array(v)
