"""INT8 quantization (reference ``python/mxnet/contrib/quantization.py``
+ ``src/operator/quantization/`` [path cites — unverified]).

TPU-first: int8 matmul/conv accumulate in int32 on the MXU
(``preferred_element_type``), so quantized FullyConnected/Convolution
are real int8 kernels, not simulation. The conversion pass rewrites the
Symbol DAG (offline weight quantization + calibrated activation ranges),
exactly the reference's ``quantize_model`` flow:

    qsym, qarg, aux = quantize_model(sym, arg_params, aux_params,
                                     calib_data=..., calib_mode='naive')
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import apply_op
from ..ndarray.ops import register_op

__all__ = ["quantize", "dequantize", "quantize_model", "quantize_net",
           "calib_thresholds"]


# ---------------------------------------------------------------------------
# primitive ops
# ---------------------------------------------------------------------------
@register_op("_contrib_quantize_v2", aliases=("quantize_v2",))
def quantize(data, min_calib_range=None, max_calib_range=None, **kwargs):
    """float → int8 + (min, max) range scalars (reference quantize_v2,
    symmetric int8)."""
    static = min_calib_range is not None and max_calib_range is not None
    if static:
        thr = max(abs(float(min_calib_range)), abs(float(max_calib_range)))

    def _f(x):
        t = jnp.float32(thr) if static else jnp.max(jnp.abs(x))
        t = jnp.maximum(t, 1e-8)
        scale = 127.0 / t
        q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
        return q, -t, t
    return apply_op(_f, [data], "quantize_v2", n_out=3)


@register_op("_contrib_dequantize", aliases=("dequantize",))
def dequantize(data, min_range, max_range, **kwargs):
    def _f(q, lo, hi):
        t = jnp.maximum(jnp.maximum(jnp.abs(lo), jnp.abs(hi)), 1e-8)
        return q.astype(jnp.float32) * (t / 127.0)
    return apply_op(_f, [data, min_range, max_range], "dequantize")


def _quantize_weight(w: onp.ndarray) -> Tuple[onp.ndarray, float]:
    thr = max(float(onp.abs(w).max()), 1e-8)
    q = onp.clip(onp.round(w * (127.0 / thr)), -127, 127).astype(onp.int8)
    return q, thr


@register_op("_contrib_quantized_fully_connected")
def quantized_fully_connected(data, weight, bias=None, num_hidden=None,
                              no_bias=False, flatten=True, w_thr=1.0,
                              calib_min=None, calib_max=None, **kwargs):
    """int8 FC: int8×int8 → int32 on the MXU, rescale to float
    (reference src/operator/quantization/quantized_fully_connected.cc).
    ``weight`` is pre-quantized int8; activations quantize on the fly
    (calibrated range when provided, dynamic otherwise)."""
    static = calib_min is not None and calib_max is not None
    a_thr = max(abs(float(calib_min)), abs(float(calib_max))) if static \
        else None
    arrs = [data, weight] + ([] if no_bias or bias is None else [bias])

    def _f(x, qw, *b):
        if flatten:
            x = x.reshape(x.shape[0], -1)
        t = jnp.float32(a_thr) if static else \
            jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
        scale = 127.0 / t
        qx = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
        acc = lax.dot_general(
            qx, qw, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (t / 127.0) * (w_thr / 127.0)
        if b:
            out = out + b[0]
        return out
    return apply_op(_f, arrs, "quantized_fc")


@register_op("_contrib_quantized_conv")
def quantized_conv(data, weight, bias=None, kernel=None, stride=None,
                   pad=None, num_filter=None, num_group=1, no_bias=False,
                   w_thr=1.0, calib_min=None, calib_max=None, **kwargs):
    """int8 convolution with int32 accumulation (reference
    quantized_conv.cc), NCHW."""
    ndim = len(kernel)
    stride = tuple(stride) if stride else (1,) * ndim
    pad_ = tuple(pad) if pad else (0,) * ndim
    static = calib_min is not None and calib_max is not None
    a_thr = max(abs(float(calib_min)), abs(float(calib_max))) if static \
        else None
    arrs = [data, weight] + ([] if no_bias or bias is None else [bias])
    spec = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}[ndim]

    def _f(x, qw, *b):
        t = jnp.float32(a_thr) if static else \
            jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
        scale = 127.0 / t
        qx = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
        acc = lax.conv_general_dilated(
            qx, qw, window_strides=stride,
            padding=[(p, p) for p in pad_], dimension_numbers=spec,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (t / 127.0) * (w_thr / 127.0)
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * ndim)
        return out
    return apply_op(_f, arrs, "quantized_conv")


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
def _kl_threshold(samples: onp.ndarray, num_bins: int = 2048,
                  num_quantized_bins: int = 255) -> float:
    """Entropy-optimal |threshold| (reference _LayerHistogramCollector's
    KL divergence calibration, simplified)."""
    mags = onp.abs(samples.ravel())
    max_val = float(mags.max()) if mags.size else 1.0
    if max_val <= 0:
        return 1.0
    hist, edges = onp.histogram(mags, bins=num_bins, range=(0, max_val))
    best_kl, best_thr = onp.inf, max_val
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 64)):
        thr = edges[i]
        p = hist[:i].astype(onp.float64).copy()
        p[-1] += hist[i:].sum()                  # clip outliers in
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = i / num_quantized_bins
        q = onp.zeros_like(p)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), int((j + 1) * factor)
            hi = max(hi, lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = onp.where(chunk > 0, chunk.sum() / nz, 0)
        pn, qn = p / p.sum(), q / max(q.sum(), 1e-12)
        mask = pn > 0
        kl = float(onp.sum(pn[mask] * onp.log(
            pn[mask] / onp.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_thr = kl, thr
    # guard the search's small-threshold degeneracy (at factor≈1 the
    # quantized histogram reproduces the clipped one exactly, KL→0):
    # never clip more than the 99.9th percentile of observed magnitude
    floor = float(onp.percentile(mags, 99.9)) if mags.size else best_thr
    return float(max(best_thr, floor))


def calib_thresholds(sym, arg_params, aux_params, calib_data,
                     data_name: str = "data", node_names: List[str] = (),
                     calib_mode: str = "naive", num_calib_batches: int = 4,
                     ctx=None) -> Dict[str, float]:
    """Run calibration batches through the fp32 graph and return
    |threshold| per requested internal output name."""
    import mxtpu.symbol as msym
    internals = sym.get_internals()
    outs = [internals[n] for n in node_names]
    group = msym.Group(outs)
    feed_shapes = {}
    calib_data.reset()
    first = next(calib_data)
    feed_shapes[data_name] = first.data[0].shape
    ex = group.bind(
        ctx or nd.NDArray(first.data[0]._data).context,
        {**{k: v for k, v in arg_params.items()},
         data_name: first.data[0]}, grad_req="null",
        aux_states=dict(aux_params))
    collected: Dict[str, List[onp.ndarray]] = {n: [] for n in node_names}
    batch = first
    for bi in range(num_calib_batches):
        outs_nd = ex.forward(is_train=False,
                             **{data_name: batch.data[0]})
        for n, o in zip(node_names, outs_nd):
            collected[n].append(o.asnumpy())
        try:
            batch = next(calib_data)
        except StopIteration:
            break
    th = {}
    for n, chunks in collected.items():
        alldata = onp.concatenate([c.ravel() for c in chunks])
        if calib_mode == "entropy":
            th[n] = _kl_threshold(alldata)
        else:
            th[n] = float(onp.abs(alldata).max())
    return th


# ---------------------------------------------------------------------------
# graph rewrite
# ---------------------------------------------------------------------------
_QUANTIZABLE = {"FullyConnected": "_contrib_quantized_fully_connected",
                "Convolution": "_contrib_quantized_conv"}


def quantize_model(sym, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray],
                   data_names=("data",), excluded_sym_names=(),
                   calib_mode: str = "none", calib_data=None,
                   num_calib_batches: int = 4, quantized_dtype="int8",
                   ctx=None):
    """Rewrite FullyConnected/Convolution to int8 (reference
    ``quantize_model``). Returns (qsym, qarg_params, aux_params)."""
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    from mxtpu.symbol.symbol import _Node, Symbol

    excluded = set(excluded_sym_names)
    targets = [n for n in sym._topo()
               if n.op in _QUANTIZABLE and n.name not in excluded]

    # calibrate activation ranges at each target's data input
    th_dict: Dict[str, float] = {}
    if calib_mode in ("naive", "entropy") and calib_data is not None:
        internals = sym.get_internals()
        input_names = {}
        for node in targets:
            input_names[node.name] = node.inputs[0][0].name
        uniq = sorted({v for v in input_names.values()
                       if v not in data_names})
        ths = calib_thresholds(sym, arg_params, aux_params, calib_data,
                               data_names[0], uniq, calib_mode,
                               num_calib_batches, ctx)
        for node_name, inp in input_names.items():
            if inp in ths:
                th_dict[node_name] = ths[inp]

    qarg_params = dict(arg_params)
    memo: Dict[int, _Node] = {}

    def clone(node: _Node) -> _Node:
        if id(node) in memo:
            return memo[id(node)]
        new_inputs = [(clone(p), i) for p, i in node.inputs]
        if node.op in _QUANTIZABLE and node.name not in excluded:
            wname = node.inputs[1][0].name
            w = arg_params[wname].asnumpy()
            qw, w_thr = _quantize_weight(w)
            # int8 codes live under a NEW arg name — a weight shared
            # with a non-quantized consumer keeps its fp32 entry
            qwname = wname + "_quantized"
            qarg_params[qwname] = nd.array(qw, dtype="int8")
            qw_var = _Node("null", qwname, {}, [])
            new_inputs = ([new_inputs[0], (qw_var, 0)] + new_inputs[2:])
            attrs = dict(node.attrs)
            attrs["w_thr"] = w_thr
            if node.name in th_dict:
                attrs["calib_min"] = -th_dict[node.name]
                attrs["calib_max"] = th_dict[node.name]
            new = _Node(_QUANTIZABLE[node.op], node.name + "_quantized",
                        attrs, new_inputs)
        else:
            new = _Node(node.op, node.name, dict(node.attrs), new_inputs)
        memo[id(node)] = new
        return new

    entries = [(clone(n), i) for n, i in sym._entries]
    qsym = Symbol(entries)
    # drop args no longer referenced (fp32 copies of fully-quantized
    # weights), keep everything the rewritten graph consumes
    live = set(qsym.list_inputs())
    qarg_params = {k: v for k, v in qarg_params.items() if k in live}
    return qsym, qarg_params, dict(aux_params)


def quantize_net(network, calib_data=None, calib_mode="naive",
                 excluded_sym_names=(), num_calib_batches=4, ctx=None,
                 data_shape=None):
    """Quantize a Gluon HybridBlock → SymbolBlock (reference
    ``quantize_net``)."""
    import os
    import tempfile

    from .. import gluon
    from ..model import load_params

    with tempfile.TemporaryDirectory(prefix="mxtpu_quant_") as tmp:
        prefix = os.path.join(tmp, "net")
        network.export(prefix)
        import mxtpu.symbol as msym
        sym = msym.load(prefix + "-symbol.json")
        arg_params, aux_params = load_params(prefix, 0)
    qsym, qargs, auxs = quantize_model(
        sym, arg_params, aux_params, calib_mode=calib_mode,
        calib_data=calib_data, excluded_sym_names=excluded_sym_names,
        num_calib_batches=num_calib_batches, ctx=ctx)
    block = gluon.SymbolBlock(qsym, [msym.var("data")],
                              params={**qargs, **auxs})
    return block
