"""Deploy path: load + run StableHLO artifacts exported by
``HybridBlock.export_stablehlo`` — the rebuild of the reference's C
predict API (``src/c_api/c_predict_api.cc`` [path cite — unverified]):
a deployment artifact runnable without the model's Python class.
"""
from __future__ import annotations

import jax

from ..ndarray import NDArray

__all__ = ["load", "Predictor"]


def load(path: str) -> "Predictor":
    """Load a ``.stablehlo`` artifact into a callable Predictor."""
    with open(path, "rb") as f:
        from jax import export as _jax_export  # lazy submodule on old jax
        exported = _jax_export.deserialize(f.read())
    return Predictor(exported)


class Predictor:
    """Callable over NDArrays (the reference PredictorHandle analogue);
    the underlying computation is the serialized StableHLO module,
    weights baked in."""

    def __init__(self, exported):
        self._exported = exported

    def __call__(self, *inputs):
        datas = [x._data if isinstance(x, NDArray) else x
                 for x in inputs]
        outs = self._exported.call(*datas)
        res = tuple(NDArray(o) for o in outs)
        return res[0] if len(res) == 1 else res
