"""mx.contrib.chaos — deterministic fault injection for the
distributed stack (docs/robustness.md).

The reference's recovery story was only ever TESTED by hand (SURVEY
§5.3: checkpoint+restart); this module is the missing verification
depth — seeded, reproducible faults driven by tier-1 tests
(tests/test_fault_tolerance.py):

- :class:`ChaosPlan` — a seeded schedule of dropped / duplicated /
  delayed PS messages, attached to a ``ServerClient`` via
  :func:`attach`. "drop_before_send" kills the connection before the
  request leaves (the request is LOST — retry must re-apply);
  "drop_after_send" kills it after the request is on the wire but
  before the ack returns (the request is APPLIED — retry is a
  duplicate delivery the server must dedup). Together they cover both
  halves of the at-most-once/at-least-once ambiguity that makes naive
  retry wrong.
- :class:`ServerProcess` — a standalone parameter server in a child
  process (``python -m mxtpu.kvstore.server``) that tests can
  ``kill()`` (SIGKILL, mid-epoch) and ``restart()`` against the same
  snapshot path.
- :class:`VirtualAllreduceKV` — an in-process N-rank lockstep cluster
  (threads + a real barrier-synchronized allreduce) for exercising
  cross-rank agreement paths (``Trainer._all_workers_finite``) without
  N processes.
- :func:`poison_nan` — NaN-poison a parameter's gradient (the AMP
  global-overflow-skip scenario).
- :func:`simulate_preemption` — deliver SIGTERM to this process, the
  scheduler-preemption notice ``checkpoint.PreemptionGuard`` absorbs.
- :class:`ServeChaosPlan` + :func:`attach_serve` — the SERVING tier's
  fault schedule (docs/robustness.md §serving): kill an engine replica
  at step N, raise inside decode dispatch, sever/delay/corrupt KV
  handoff frames, kill a prefill worker — attached to a live gateway,
  so supervision, deterministic re-dispatch, channel self-healing and
  the circuit breaker are all provoked in tier-1 tests
  (tests/test_serve_chaos.py) rather than trusted.

Everything is seeded and thread-free on the decision path, so a chaos
run is exactly reproducible — ci/runtime_functions.sh proves it by
rerunning both suites under tools/flakiness_checker.py
(``fault_tolerance`` and ``chaos_serve`` stages).
"""
from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ChaosPlan", "attach", "ServerProcess", "VirtualAllreduceKV",
           "poison_nan", "simulate_preemption",
           "ServeChaosFault", "ServeChaosPlan", "attach_serve",
           "TrainChaosFault", "TrainChaosPlan", "SimTrainHost",
           "attach_train"]


class ChaosPlan:
    """Seeded fault schedule for PS client requests.

    Faults come from an explicit ``schedule`` (request index → action)
    and/or seeded per-request probabilities. Actions:

    - ``"drop_before_send"``: close the socket, raise — the request
      never reaches the server (a lost message).
    - ``"drop_after_send"``: let the request go out, then close the
      socket before the reply is read — the server applied it but the
      worker doesn't know (a lost ack → the retry is a duplicate
      delivery).
    - ``"delay"``: sleep ``delay_s`` before sending (reordering
      pressure on the heartbeat/timeout machinery).

    ``injected`` counts what actually fired, for test assertions."""

    ACTIONS = ("drop_before_send", "drop_after_send", "delay")

    def __init__(self, seed: int = 0,
                 schedule: Optional[Dict[int, str]] = None,
                 drop_before_send: float = 0.0,
                 drop_after_send: float = 0.0,
                 delay: float = 0.0, delay_s: float = 0.02,
                 max_faults: Optional[int] = None):
        self._rng = random.Random(seed)
        self._schedule = dict(schedule or {})
        for a in self._schedule.values():
            if a not in self.ACTIONS:
                raise ValueError(f"unknown chaos action {a!r}")
        self._p = {"drop_before_send": drop_before_send,
                   "drop_after_send": drop_after_send,
                   "delay": delay}
        self._delay_s = delay_s
        self._max_faults = max_faults
        self.requests = 0           # request attempts seen (incl. retries)
        self._req_index = 0         # fresh requests (retries not counted)
        self._pending_after: bool = False
        self.injected: Dict[str, int] = {a: 0 for a in self.ACTIONS}

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def _decide(self) -> Optional[str]:
        if self._max_faults is not None and \
                self.total_injected >= self._max_faults:
            return None
        if self._req_index in self._schedule:
            return self._schedule[self._req_index]
        for action in self.ACTIONS:
            p = self._p[action]
            if p > 0.0 and self._rng.random() < p:
                return action
        return None

    # -- ServerClient hooks -------------------------------------------------
    def on_request(self, client) -> None:
        """Called with the client's lock held, before the frame is
        sent. Retries re-enter here: only the FIRST attempt of each
        request consumes a schedule slot, so a fault schedule indexes
        logical requests, not wire attempts."""
        self.requests += 1
        action = None
        if not getattr(client, "_chaos_retrying", False):
            action = self._decide()
            self._req_index += 1
        client._chaos_retrying = True   # cleared by on_sent
        self._pending_after = action == "drop_after_send"
        if action == "drop_before_send":
            self.injected[action] += 1
            client._drop_socket()
            raise ConnectionError("chaos: injected drop before send")
        if action == "delay":
            self.injected[action] += 1
            time.sleep(self._delay_s)

    def on_sent(self, client) -> None:
        """Called after the frame hit the wire, before the reply is
        read. The retry flag is NOT cleared here — a real recv failure
        after a clean send (server killed mid-reply) still makes the
        next attempt a retry of the same logical request, so it must
        not consume a fresh schedule slot; the client resets the flag
        when a NEW envelope starts (ServerClient._roundtrip)."""
        if self._pending_after:
            self._pending_after = False
            self.injected["drop_after_send"] += 1
            # give the server a beat to consume the frame before the
            # teardown races it (localhost: it is already in its
            # buffer; the sleep only derisks scheduling)
            time.sleep(0.05)
            client._drop_socket()
            raise ConnectionError("chaos: injected drop after send")


def attach(client_or_kvstore, plan: ChaosPlan) -> ChaosPlan:
    """Wire a ChaosPlan into a ``ServerClient`` (or an
    ``AsyncDistKVStore``, whose ``_client`` is used)."""
    client = getattr(client_or_kvstore, "_client", client_or_kvstore)
    client.chaos = plan
    client._chaos_retrying = False
    return plan


class ServerProcess:
    """A standalone parameter server in a child process, with
    kill()/restart() for crash-recovery tests.

    The child runs ``python -m mxtpu.kvstore.server`` with a snapshot
    path, so SIGKILL + ``restart()`` exercises the real recovery path:
    snapshot reload + client retry + seq dedup."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 snapshot_path: Optional[str] = None,
                 snapshot_every: int = 1,
                 env: Optional[dict] = None, start_timeout: float = 90.0):
        if port == 0:
            port = free_port(host)
        self.host, self.port = host, port
        self.snapshot_path = snapshot_path
        self._snapshot_every = snapshot_every
        self._env = {**os.environ, **(env or {})}
        # the child must never grab the accelerator: it is a numpy
        # host-side store
        self._env.setdefault("JAX_PLATFORMS", "cpu")
        self._start_timeout = start_timeout
        self.proc: Optional[subprocess.Popen] = None
        self.start()

    def _cmd(self) -> List[str]:
        cmd = [sys.executable, "-m", "mxtpu.kvstore.server",
               "--host", self.host, "--port", str(self.port)]
        if self.snapshot_path:
            cmd += ["--snapshot-path", self.snapshot_path,
                    "--snapshot-every", str(self._snapshot_every)]
        return cmd

    def start(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            return
        self.proc = subprocess.Popen(
            self._cmd(), env=self._env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.wait_ready()

    def wait_ready(self) -> None:
        """Block until the child answers a heartbeat ping."""
        from ..kvstore.server import ServerClient
        deadline = time.monotonic() + self._start_timeout
        while True:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"chaos server exited rc={self.proc.returncode} "
                    "before becoming ready")
            try:
                cl = ServerClient(self.host, self.port, timeout=2.0)
                try:
                    cl.ping(timeout=2.0)
                finally:
                    cl.close()
                return
            except Exception:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)

    def kill(self) -> None:
        """SIGKILL — the unclean mid-epoch crash. No snapshot flush, no
        goodbye: recovery rides whatever already hit the disk."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def restart(self) -> None:
        self.kill()
        self.start()

    def stop(self) -> None:
        """Graceful SIGTERM (flushes a final snapshot) with a SIGKILL
        fallback."""
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()

    def __enter__(self) -> "ServerProcess":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (best-effort: released before use,
    like every test-harness port picker)."""
    import socket as _socket
    s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class VirtualAllreduceKV:
    """An in-process N-rank cluster whose ``_allreduce`` is a REAL
    barrier-synchronized sum across N rank threads — the cheapest
    honest way to exercise cross-rank agreement logic
    (``Trainer._all_workers_finite``) on one host.

    Each rank thread registers itself via ``run(fn)``; inside ``fn``,
    any Trainer handed this object as its kvstore participates in
    lockstep allreduces with the other ranks. Deadlocks by design if
    ranks disagree on how many collectives they issue — which is
    exactly the divergence bug the global-skip path exists to
    prevent."""

    def __init__(self, num_workers: int):
        self.num_workers = num_workers
        self._barrier = threading.Barrier(num_workers)
        self._contrib: List = [None] * num_workers
        self._result = None
        self._tls = threading.local()

    # Trainer probes these
    @property
    def rank(self) -> int:
        return getattr(self._tls, "rank", 0)

    def _allreduce(self, value):
        """SUM ``value`` (an NDArray) across all rank threads."""
        import numpy as onp
        from .. import ndarray as nd
        rank = self._tls.rank
        self._contrib[rank] = onp.asarray(value.asnumpy())
        if self._barrier.wait() == 0:          # all deposited
            self._result = sum(self._contrib)
        self._barrier.wait()                   # result published
        # safe to read until every rank re-enters the next allreduce's
        # first barrier — which requires every rank to have read
        return nd.array(self._result)

    def run(self, fn: Callable[[int], None], timeout: float = 120.0):
        """Run ``fn(rank)`` on ``num_workers`` threads in lockstep;
        re-raise the first rank's exception."""
        errors: List = [None] * self.num_workers

        def _runner(rank):
            self._tls.rank = rank
            try:
                fn(rank)
            except BaseException as e:   # noqa: BLE001 — reported below
                errors[rank] = e
                self._barrier.abort()    # release peers blocked on us

        threads = [threading.Thread(target=_runner, args=(r,), daemon=True)
                   for r in range(self.num_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
            if t.is_alive():
                self._barrier.abort()
                raise TimeoutError(
                    "virtual cluster rank hung (collective mismatch?)")
        real = [e for e in errors
                if e is not None
                and not isinstance(e, threading.BrokenBarrierError)]
        if real:
            raise real[0]
        broken = [e for e in errors if e is not None]
        if broken:                      # every error was a barrier break
            raise broken[0]             # with no root cause recorded
        return None


class ServeChaosFault(RuntimeError):
    """The injected failure ``ServeChaosPlan`` raises inside serving
    threads — distinct from real faults so a test log reads
    honestly."""


class ServeChaosPlan:
    """Seeded, schedule-driven fault injection for the SERVING tier
    (the gateway sibling of :class:`ChaosPlan`; docs/robustness.md
    §serving). Attach to a LIVE gateway with :func:`attach_serve`;
    every action fires at a deterministic point, so a chaos run is
    exactly reproducible (the ``chaos_serve`` CI stage proves it under
    tools/flakiness_checker.py):

    - ``kill_replica`` — {replica index: engine step}: the replica's
      serving thread dies (an exception escaping its loop) when its
      engine reaches that step — mid-decode, with requests seated.
    - ``raise_in_decode`` — {replica index: dispatch count}: raises
      inside the decode dispatch path instead (same death, different
      stack — both must end in supervision + re-dispatch).
    - ``kv_frames`` — {handoff frame index: action} on the disagg
      channel's send side: ``"sever"`` (connection torn down
      mid-handoff → reconnect + HMAC re-auth + resend), ``"delay"``
      (sleep ``delay_s``), ``"corrupt"`` (an unverifiable frame on
      the wire ahead of the real one → the receiver quarantines the
      connection, the sender reconnects and resends).
    - ``kill_prefill`` — {worker index: job index}: the prefill
      worker thread dies mid-pool (→ respawn + single resubmit).

    ``injected`` counts what actually fired, for test assertions.
    Replacement replicas/workers spawned by the supervisor are NOT
    re-wrapped — each scheduled fault fires at most once."""

    KV_ACTIONS = ("sever", "delay", "corrupt")

    def __init__(self, seed: int = 0,
                 kill_replica: Optional[Dict[int, int]] = None,
                 raise_in_decode: Optional[Dict[int, int]] = None,
                 kv_frames: Optional[Dict[int, str]] = None,
                 kill_prefill: Optional[Dict[int, int]] = None,
                 delay_s: float = 0.02):
        self._rng = random.Random(seed)
        self.kill_replica = dict(kill_replica or {})
        self.raise_in_decode = dict(raise_in_decode or {})
        self.kv_frames = dict(kv_frames or {})
        for a in self.kv_frames.values():
            if a not in self.KV_ACTIONS:
                raise ValueError(f"unknown kv chaos action {a!r}")
        self.kill_prefill = dict(kill_prefill or {})
        self.delay_s = delay_s
        self._kv_index = 0
        self._kv_lock = threading.Lock()
        self.injected: Dict[str, int] = {
            "replica_kill": 0, "decode_raise": 0, "kv_sever": 0,
            "kv_delay": 0, "kv_corrupt": 0, "prefill_kill": 0}

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    # -- wrapping ------------------------------------------------------------
    def _wrap_dispatch(self, replica, kill_step: Optional[int],
                       raise_at: Optional[int]) -> None:
        engine = replica.engine
        orig = engine._dispatch
        calls = {"n": 0}
        plan = self

        def chaotic_dispatch(firsts):
            if kill_step is not None \
                    and engine.steps_run >= kill_step:
                plan.injected["replica_kill"] += 1
                raise ServeChaosFault(
                    f"chaos: replica {replica.name} killed at step "
                    f"{engine.steps_run}")
            if raise_at is not None and calls["n"] >= raise_at:
                plan.injected["decode_raise"] += 1
                raise ServeChaosFault(
                    f"chaos: raised inside decode dispatch of "
                    f"{replica.name}")
            calls["n"] += 1
            return orig(firsts)

        engine._dispatch = chaotic_dispatch

    def _wrap_channel(self, channel) -> None:
        orig = channel.send_handoff
        plan = self

        def chaotic_send(msg):
            with plan._kv_lock:
                idx = plan._kv_index
                plan._kv_index += 1
                action = plan.kv_frames.pop(idx, None)
            if action == "sever":
                plan.injected["kv_sever"] += 1
                sock = channel._sock
                if sock is not None:
                    try:
                        sock.shutdown(2)    # mid-handoff connection cut
                    except OSError:
                        pass
                    sock.close()
            elif action == "delay":
                plan.injected["kv_delay"] += 1
                time.sleep(plan.delay_s)
            elif action == "corrupt":
                plan.injected["kv_corrupt"] += 1
                sock = channel._sock
                if sock is not None:
                    from mxtpu import rpc as _rpc
                    try:
                        # a frame MAC'd with the wrong key: fails the
                        # receiver's HMAC check, poisoning the
                        # connection ahead of the real handoff
                        _rpc.send_msg(sock, ("kv", -1, 0, 0),
                                      b"chaos-wrong-secret")
                    except OSError:
                        pass
            return orig(msg)

        channel.send_handoff = chaotic_send

    def _wrap_worker(self, worker, job_index: int) -> None:
        orig = worker._one
        jobs = {"n": 0}
        plan = self

        def chaotic_one(rid, req):
            n = jobs["n"]
            jobs["n"] += 1
            if n == job_index:
                plan.injected["prefill_kill"] += 1
                raise ServeChaosFault(
                    f"chaos: prefill worker {worker.name} killed at "
                    f"job {n}")
            return orig(rid, req)

        worker._one = chaotic_one


def attach_serve(gateway, plan: ServeChaosPlan) -> ServeChaosPlan:
    """Wire a :class:`ServeChaosPlan` into a LIVE gateway: wraps the
    scheduled replicas' dispatch paths, the disagg KV channel's send
    side, and the scheduled prefill workers. Accepts a ``Gateway`` or
    a bare backend (``ReplicaSet`` / ``DisaggBackend``)."""
    backend = getattr(gateway, "backend", gateway)
    replicas = backend.replicas() if hasattr(backend, "replicas") \
        else []
    for idx in sorted(set(plan.kill_replica) | set(plan.raise_in_decode)):
        if idx >= len(replicas):
            raise ValueError(
                f"chaos plan targets replica {idx}; backend has "
                f"{len(replicas)}")
        plan._wrap_dispatch(replicas[idx],
                            plan.kill_replica.get(idx),
                            plan.raise_in_decode.get(idx))
    if plan.kv_frames or plan.kill_prefill:
        workers = getattr(backend, "prefill", None)
        tx = getattr(backend, "_tx", None)
        if workers is None or tx is None:
            raise ValueError(
                "kv/prefill chaos needs a DisaggBackend gateway")
        if plan.kv_frames:
            plan._wrap_channel(tx)
        for idx, job in plan.kill_prefill.items():
            if idx >= len(workers):
                raise ValueError(
                    f"chaos plan targets prefill worker {idx}; pool "
                    f"has {len(workers)}")
            plan._wrap_worker(workers[idx], job)
    return plan


class TrainChaosFault(RuntimeError):
    """The injected failure :class:`TrainChaosPlan` raises inside the
    elastic train loop — a simulated host death escaping
    ``ElasticTrainer.run``, so the test relaunches a fresh driver
    exactly like a real crash would."""


class SimTrainHost:
    """A simulated PEER host in the elastic control plane: a real
    :class:`~mxtpu.parallel.elastic.ElasticMember` over real TCP, with
    three failure knobs —

    - :meth:`kill` — stop heartbeating WITHOUT a goodbye (the kill -9
      / eviction case; the coordinator declares it lost after
      ``MXTPU_ELASTIC_LOST_AFTER_S``);
    - :meth:`leave` — graceful SIGTERM-drain departure;
    - :meth:`freeze` — keep heartbeating but stop advancing the
      reported step (the slow host the straggler detector evicts).

    A watcher thread auto-rejoins on resize notices (a live fleet's
    survivors all re-rendezvous; without this the barrier would wait
    on the simulated peer forever). ``advance(step)`` mirrors the
    driver's progress so the sim host keeps pace in normal times."""

    def __init__(self, host_id: str, address, heartbeat_s=None,
                 secret=None):
        from ..parallel.elastic import ElasticMember
        self.host_id = host_id
        self._member = ElasticMember(host_id, address,
                                     heartbeat_s=heartbeat_s,
                                     secret=secret)
        self._frozen = False
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    def join(self) -> int:
        gen = self._member.join()
        if self._watcher is None:
            self._watcher = threading.Thread(
                target=self._watch, daemon=True,
                name=f"sim-host:{self.host_id}")
            self._watcher.start()
        return gen

    def _watch(self) -> None:
        from ..parallel.elastic import ElasticError
        while not self._stop.wait(0.05):
            if self._member.resize_pending.is_set():
                try:
                    self._member.rejoin()
                except (ElasticError, ConnectionError, OSError):
                    pass

    def advance(self, step: int) -> None:
        if not self._frozen:
            self._member.report_step(step)

    def freeze(self) -> None:
        self._frozen = True

    def kill(self) -> None:
        """Silent death: heartbeats stop, no leave message."""
        self._stop.set()
        self._member._stop.set()

    def leave(self) -> None:
        self._stop.set()
        self._member.leave()

    @property
    def generation(self) -> int:
        return self._member.generation


class TrainChaosPlan:
    """Seeded, schedule-driven fault injection for ELASTIC TRAINING
    (the train-side sibling of :class:`ServeChaosPlan`; docs/
    robustness.md §"Elastic training"). Attach to a live
    ``ElasticTrainer`` with :func:`attach_train`; every action fires at
    a deterministic step, so a chaos run is exactly reproducible (the
    ``chaos_train`` CI stage proves it under flakiness_checker):

    - ``kill_at`` — step N: THIS process's training loop dies (a
      :class:`TrainChaosFault` escaping ``run()``); the test relaunches
      a fresh driver, which must resume from the last committed
      checkpoint+journal bit-identically.
    - ``sigterm_at`` — step N: deliver SIGTERM to this process (the
      scheduler preemption notice ``PreemptionGuard`` absorbs →
      final synchronous save + clean return).
    - ``kill_host_at`` — {host_id: step}: a simulated PEER host goes
      silent → coordinator eviction → generation bump → the driver
      resizes and resumes at the new world size.
    - ``slow_host_at`` — {host_id: step}: the peer freezes its step
      progress → straggler detection → same resize path.
    - ``nan_at`` — steps whose batch is NaN-poisoned (drives the
      in-program nonfinite skip / rollback guard).
    - ``torn_checkpoint_at`` — step N: after the save at step N
      commits, every file in its step directory is overwritten with
      garbage (a kill mid-write torn worse than orbax's commit
      protocol can clean) — restore must fall back to the previous
      retained step, loudly.

    ``injected`` counts what actually fired, for test assertions."""

    def __init__(self, seed: int = 0,
                 kill_at: Optional[int] = None,
                 sigterm_at: Optional[int] = None,
                 kill_host_at: Optional[Dict[str, int]] = None,
                 slow_host_at: Optional[Dict[str, int]] = None,
                 nan_at: Optional[List[int]] = None,
                 torn_checkpoint_at: Optional[int] = None):
        self._rng = random.Random(seed)
        self.kill_at = kill_at
        self.sigterm_at = sigterm_at
        self.kill_host_at = dict(kill_host_at or {})
        self.slow_host_at = dict(slow_host_at or {})
        self.nan_at = set(nan_at or ())
        self.torn_checkpoint_at = torn_checkpoint_at
        self.injected: Dict[str, int] = {
            "kill": 0, "sigterm": 0, "host_kill": 0, "host_slow": 0,
            "nan": 0, "torn_checkpoint": 0}

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def _poison_batch(self, batch):
        """Replace every array leaf of the batch with NaNs of the same
        shape/dtype (works for the functional path's pytree batch and
        the fused path's tuple-of-arrays batch alike)."""
        import jax
        import jax.numpy as jnp

        def nanlike(x):
            a = jnp.asarray(getattr(x, "_data", x))
            if not jnp.issubdtype(a.dtype, jnp.floating):
                return x
            return jnp.full(a.shape, jnp.nan, dtype=a.dtype)

        return jax.tree.map(nanlike, batch)

    def _tear_step_dir(self, step: int, directory: str) -> None:
        d = os.path.join(directory, str(int(step)))
        for root, _, files in os.walk(d):
            for name in files:
                try:
                    with open(os.path.join(root, name), "wb") as f:
                        f.write(b"torn by chaos")
                except OSError:
                    pass
        self.injected["torn_checkpoint"] += 1


def attach_train(trainer, plan: TrainChaosPlan,
                 hosts: Optional[Dict[str, SimTrainHost]] = None
                 ) -> TrainChaosPlan:
    """Wire a :class:`TrainChaosPlan` into a live
    ``ElasticTrainer`` (its ``pre_step_hooks``/``post_save_hooks``)
    and the simulated peer ``hosts`` the host-level faults target."""
    hosts = dict(hosts or {})
    for hid in list(plan.kill_host_at) + list(plan.slow_host_at):
        if hid not in hosts:
            raise ValueError(
                f"chaos plan targets host {hid!r} but no such "
                f"SimTrainHost was passed (have {sorted(hosts)})")

    def pre_step(i, batch):
        for hid, at in list(plan.kill_host_at.items()):
            if i >= at:
                plan.injected["host_kill"] += 1
                del plan.kill_host_at[hid]
                hosts[hid].kill()
        for hid, at in list(plan.slow_host_at.items()):
            if i >= at:
                plan.injected["host_slow"] += 1
                del plan.slow_host_at[hid]
                hosts[hid].freeze()
        for h in hosts.values():
            h.advance(i)
        if plan.sigterm_at is not None and i >= plan.sigterm_at:
            plan.sigterm_at = None
            plan.injected["sigterm"] += 1
            simulate_preemption()
        if plan.kill_at is not None and i >= plan.kill_at:
            plan.kill_at = None
            plan.injected["kill"] += 1
            raise TrainChaosFault(f"chaos: train host killed at "
                                  f"step {i}")
        if i in plan.nan_at:
            plan.injected["nan"] += 1
            return plan._poison_batch(batch)
        return batch

    def post_save(step, directory):
        if plan.torn_checkpoint_at is not None and \
                step == plan.torn_checkpoint_at:
            plan.torn_checkpoint_at = None
            trainer.manager.wait_until_finished()
            plan._tear_step_dir(step, directory)

    trainer.pre_step_hooks.append(pre_step)
    trainer.post_save_hooks.append(post_save)
    return plan


def poison_nan(param) -> None:
    """Overwrite a parameter's gradient with NaNs — the poisoned-rank
    half of the AMP global-overflow scenario."""
    import jax.numpy as jnp
    g = param.grad()
    g._set_data(jnp.full(g.shape, jnp.nan, dtype=g._data.dtype))


def simulate_preemption(sig: int = signal.SIGTERM) -> None:
    """Deliver the scheduler's preemption notice to THIS process (the
    signal ``checkpoint.PreemptionGuard`` absorbs)."""
    os.kill(os.getpid(), sig)
