"""The fleet control plane: multi-model, multi-tenant serving over
the PR 6–8 gateway (docs/serving.md §fleet).

One :class:`FleetGateway` fronts N named models. Each model gets its
OWN full gateway stack — journal, supervisor, SLO tracker, shed tiers
— over a :class:`FleetPool` (a versioned :class:`ReplicaSet`); the
fleet layer adds what no single-model gateway can do:

- **named-model routing**: ``model=`` in the request body picks the
  pool; per-model series (``gateway_requests_total{model}``,
  ``gateway_ttft_ms{model}``, per-model SLO gauges) coexist in one
  registry, single-model series names grandfathered unchanged;
- **chip arbitration**: one :class:`~.arbiter.FleetArbiter` moves
  replicas' worth of chips between pools by SLO burn + queue
  pressure, replacing per-model autoscaling;
- **priority classes**: ``priority=interactive|batch|offline`` rides
  the gateway's shed tiers — low classes see a fraction of the queue
  bound and yield outright under SLO burn;
- **live checkpoint hot-swap** (:meth:`FleetGateway.hot_swap`): new
  weights in, zero accepted requests dropped — surge a fresh replica
  per old one, drain the old (it finishes everything it accepted, on
  the old build: bit-identity holds), version label on every
  response;
- **session affinity**: a returning ``session_id`` lands on the
  replica already KV-warm for it (bounded LRU map, hit/miss
  counters).

The front door is the EXISTING ``frontdoor.serve_http`` — the fleet
gateway implements the same four-method surface (``submit_dict`` /
``health`` / ``state`` / ``metrics_text``), so clients, the chaos
harness and ``tools/diagnose.py`` all work unchanged.
"""
from __future__ import annotations

import inspect
import itertools
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ... import telemetry
from ...base import env_int, env_str
from ...telemetry import distributed as dtrace
from ...telemetry.perfscope import goodput_gauge
from ..engine import ServeEngine
from ..gateway.gateway import Gateway
from ..gateway.replica import GatewayClosed, ReplicaSet
from .arbiter import ArbiterPolicy, FleetArbiter

__all__ = ["ModelSpec", "FleetPool", "FleetGateway"]


@dataclass
class ModelSpec:
    """One named model of the fleet: how to build its engines, its
    initial/bounded pool size, its chip cost, and its SLO targets.

    ``engine_factory`` must be zero-arg callable; to hot-swap by
    ``params=``/``path=`` it must ALSO accept a ``params=`` keyword
    (write it ``lambda params=params0: ServeEngine(cfg, params,
    ...)`` — the swap calls it with the reloaded weights)."""

    name: str
    engine_factory: Callable[..., ServeEngine]
    replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 4
    chips_per_replica: int = 1
    version: str = "v0"
    queue_max: Optional[int] = None
    # per-model SLO targets (SLOTracker.from_spec keys: ttft_ms,
    # token_ms, burn, window_s); None falls back to the env knobs
    slo: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if not self.name or any(c in self.name for c in '"\n '):
            raise ValueError(f"bad model name {self.name!r} (label "
                             f"value: no quotes/whitespace)")
        if self.replicas < 1:
            raise ValueError(f"{self.name}: need >= 1 replica")
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"{self.name}: bad replica bounds "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if not (self.min_replicas <= self.replicas
                <= self.max_replicas):
            raise ValueError(
                f"{self.name}: initial replicas {self.replicas} "
                f"outside [{self.min_replicas}, "
                f"{self.max_replicas}]")
        if self.chips_per_replica < 1:
            raise ValueError(f"{self.name}: chips_per_replica >= 1")


class FleetPool(ReplicaSet):
    """A model's replica pool: a :class:`ReplicaSet` whose replicas
    carry the pool's current BUILD VERSION (stamped at spawn — the
    hot-swap seam every response labels) and whose scaling bounds /
    chip cost the fleet arbiter reads."""

    def __init__(self, spec: ModelSpec, *, started: bool = True):
        self.spec = spec
        self.model = spec.name
        self.version = spec.version
        self.chips_per_replica = spec.chips_per_replica
        self.min_replicas = spec.min_replicas
        self.max_replicas = spec.max_replicas
        super().__init__(spec.engine_factory, spec.replicas,
                         started=started,
                         name_prefix=f"{spec.name}:r",
                         labels={"model": spec.name})

    def _new_replica(self):
        r = super()._new_replica()
        # version rides the replica AND its engine: route() filters
        # on the former for same-build resume, trace events carry the
        # latter so timelines show which build served each segment
        r.version = self.version
        r.engine.build = self.version
        return r


class _ModelEntry:
    __slots__ = ("spec", "pool", "gateway", "swap_seq", "last_good",
                 "canary")

    def __init__(self, spec: ModelSpec, pool: FleetPool,
                 gateway: Gateway):
        self.spec = spec
        self.pool = pool
        self.gateway = gateway
        self.swap_seq = itertools.count(1)
        # rollback anchor: (engine_factory, version) of the build that
        # last served cleanly — captured before any swap touches the
        # pool, restored verbatim by rollback()
        self.last_good: Optional[tuple] = None
        # live canary descriptor (None outside a canary window)
        self.canary: Optional[Dict[str, Any]] = None


class FleetGateway:
    """N named models behind ONE front door on one chip budget.

    ``models``: the :class:`ModelSpec` list. ``arbiter``: an
    :class:`~.arbiter.ArbiterPolicy` (or dict of its fields) enabling
    the background arbitration loop; None disables (tests drive
    :attr:`arbiter` ticks directly after constructing their own).
    ``chip_budget`` overrides the policy's (0 = derived from the
    initial allocation). Remaining kwargs forward to each per-model
    :class:`Gateway` (supervision, queue bound default, clock)."""

    def __init__(self, models: Sequence[ModelSpec], *,
                 arbiter=None, chip_budget: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 supervise: bool = True,
                 supervisor_opts: Optional[Dict[str, Any]] = None,
                 federate=None, started: bool = True,
                 clock: Optional[Callable[[], float]] = None):
        if not models:
            raise ValueError("need at least one ModelSpec")
        self._clock = clock or time.monotonic
        self._closed = False
        self._models: "OrderedDict[str, _ModelEntry]" = OrderedDict()
        for spec in models:
            if spec.name in self._models:
                raise ValueError(f"duplicate model {spec.name!r}")
            pool = FleetPool(spec, started=started)
            gw = Gateway(backend=pool, model=spec.name,
                         queue_max=(spec.queue_max
                                    if spec.queue_max is not None
                                    else queue_max),
                         slo=spec.slo, supervise=supervise,
                         supervisor_opts=supervisor_opts,
                         federate=[],   # the FLEET federates, once
                         clock=clock)
            self._models[spec.name] = _ModelEntry(spec, pool, gw)
        # session affinity: bounded LRU of (model, session) -> the
        # replica name that served it last (KV-warm for the session's
        # running context)
        self._aff_lock = threading.Lock()
        self._affinity: "OrderedDict[tuple, str]" = OrderedDict()
        self._aff_max = env_int(
            "MXTPU_FLEET_SESSIONS_MAX", 4096,
            "Bound on the fleet session-affinity map (LRU evicted): "
            "returning session_ids route to the replica that served "
            "them last.")
        self._m_aff: Dict[str, Any] = {}
        self._m_swap: Dict[str, Any] = {}
        self._m_canary: Dict[str, Any] = {}
        self._m_rollback: Dict[tuple, Any] = {}
        # attached FlywheelControllers by model (continuous-deployment
        # state surfaced in /healthz + /state; see flywheel.py)
        self._flywheels: Dict[str, Any] = {}
        # the fleet federates ONCE (per-model gateways get no peers):
        # same env knob + secret discipline as the single-model door
        if federate is None:
            federate = env_str(
                "MXTPU_TELEMETRY_FEDERATE", "",
                "Comma-separated host:port list of peer "
                "RegistryServer endpoints the gateway /metrics "
                "federates (per-process series labelled "
                "process=<role>, plus exact aggregate series).")
        self._federate = Gateway._parse_peers(federate)
        self._fed_secret = env_str("MXTPU_GATEWAY_SECRET",
                                   "").encode()
        self._g_goodput = goodput_gauge("fleet")
        self._prev_req: Optional[tuple] = None
        self._http = None
        self.arbiter: Optional[FleetArbiter] = None
        self._arbiter_stop: Optional[threading.Event] = None
        if arbiter is not None:
            policy = (arbiter if isinstance(arbiter, ArbiterPolicy)
                      else ArbiterPolicy(**dict(arbiter)))
            if chip_budget is not None:
                policy.chip_budget = int(chip_budget)
            self.arbiter = FleetArbiter(self._models, policy,
                                        clock=clock)
            self._arbiter_stop = threading.Event()
            threading.Thread(target=self.arbiter.run_forever,
                             args=(self._arbiter_stop,), daemon=True,
                             name="mxtpu-fleet-arbiter").start()

    # -- registry ------------------------------------------------------------
    def models(self) -> List[str]:
        return list(self._models)

    def gateway(self, model: str) -> Gateway:
        """The per-model gateway (tests/tools; raises on unknown)."""
        return self._entry(model).gateway

    def pool(self, model: str) -> FleetPool:
        return self._entry(model).pool

    def _entry(self, model: Optional[str]) -> _ModelEntry:
        if model is None:
            if len(self._models) == 1:
                return next(iter(self._models.values()))
            raise ValueError(
                f"missing 'model'; this fleet serves "
                f"{list(self._models)}")
        entry = self._models.get(model)
        if entry is None:
            raise ValueError(f"unknown model {model!r}; serving "
                             f"{list(self._models)}")
        return entry

    # -- session affinity ----------------------------------------------------
    def _count_aff(self, result: str) -> None:
        m = self._m_aff.get(result)
        if m is None:
            m = self._m_aff[result] = telemetry.counter(
                "fleet_session_affinity_total",
                "Session-affinity lookups at the fleet router: hit = "
                "routed to the remembered warm replica, miss = first "
                "sight or the replica is gone, prefix = the session "
                "map missed but prefix-page affinity found a replica "
                "already holding the prompt's pages", result=result)
        m.inc()

    def _affinity_get(self, model: str,
                      session: Optional[str]) -> Optional[str]:
        if session is None:
            return None
        with self._aff_lock:
            return self._affinity.get((model, session))

    def _affinity_record(self, model: str, session: Optional[str],
                         prefer: Optional[str], handle) -> None:
        if session is None:
            return
        rep = getattr(handle.ticket, "replica", None)
        name = getattr(rep, "name", None)
        if name is None:
            return
        self._count_aff("hit" if prefer is not None
                        and name == prefer else "miss")
        key = (model, session)
        with self._aff_lock:
            self._affinity[key] = name
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._aff_max:
                self._affinity.popitem(last=False)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               model: Optional[str] = None,
               session_id: Optional[str] = None, **kw):
        """Direct-API submission (the HTTP path is
        :meth:`submit_dict`): resolves the model, applies session
        affinity, delegates to that model's gateway — every per-model
        admission rule (priority classes, shed tiers, SLO burn)
        applies there."""
        entry = self._entry(model)
        session = None if session_id is None else str(session_id)
        sess_prefer = self._affinity_get(entry.spec.name, session)
        prefer = sess_prefer
        if prefer is None:
            # session map missed (stale entry, evicted, or no
            # session_id at all): fall back to prefix-page affinity —
            # the per-model gateway knows which replica's paged cache
            # already holds this prompt's head, so a returning
            # conversation still lands on its warm pages
            prefer = entry.gateway.prefix_prefer(prompt)
            if prefer is not None:
                self._count_aff("prefix")
        handle = entry.gateway.submit(
            prompt, max_new_tokens, prefer_replica=prefer, **kw)
        self._affinity_record(entry.spec.name, session, sess_prefer,
                              handle)
        return handle

    def submit_dict(self, body: Dict[str, Any],
                    trace_id: Optional[str] = None):
        """The front door's JSON surface: ``model`` picks the pool
        (optional only for a one-model fleet), ``session_id`` routes
        a returning session to its warm replica, everything else is
        the per-model gateway's contract unchanged."""
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        model = body.get("model")
        entry = self._entry(None if model is None else str(model))
        session = body.get("session_id")
        session = None if session is None else str(session)
        sess_prefer = self._affinity_get(entry.spec.name, session)
        prefer = sess_prefer
        if prefer is None and body.get("prompt") is not None:
            # same prefix-page fallback as submit(): a session-map
            # miss still routes to the replica holding warm pages
            prefer = entry.gateway.prefix_prefer(body["prompt"])
            if prefer is not None:
                self._count_aff("prefix")
        handle = entry.gateway.submit_dict(body, trace_id=trace_id,
                                           prefer_replica=prefer)
        self._affinity_record(entry.spec.name, session, sess_prefer,
                              handle)
        return handle

    # -- hot swap ------------------------------------------------------------
    def hot_swap(self, model: str, *, params: Any = None,
                 path: Optional[str] = None,
                 engine_factory: Optional[Callable[[],
                                                   ServeEngine]] = None,
                 version: Optional[str] = None,
                 drain_timeout_s: float = 120.0) -> Dict[str, Any]:
        """Replace a pool's weights LIVE, dropping nothing: for each
        old replica, a fresh one is spawned from the new build FIRST
        (capacity never dips below the allocation), then the old one
        is drained — it finishes every request it accepted, on the
        old build, so completed streams stay bit-identical to a
        fault-free old-build run. New requests route to the
        least-loaded (fresh) replicas; every response's ``version``
        field names the build that produced it.

        New weights come from exactly one of: ``params`` (a pytree),
        ``path`` (a PR 11 ``checkpoint.save_state`` snapshot —
        reloaded here), or ``engine_factory`` (full control).
        ``version`` defaults to ``v<n>`` counting per model."""
        entry = self._entry(model)
        pool = entry.pool
        engine_factory = self._resolve_factory(
            entry, params=params, path=path,
            engine_factory=engine_factory)
        version = version or f"v{next(entry.swap_seq)}"
        old = pool.replicas()
        old_version = pool.version
        entry.last_good = (pool._factory, old_version)
        entry.canary = None            # a full swap ends any canary
        pool.set_factory(engine_factory, version)
        telemetry.flight().record(
            "fleet", "swap_begin", model=model,
            from_version=old_version, to_version=version,
            replicas=len(old))
        swapped, still = self._swap_out(entry, old, drain_timeout_s)
        m = self._m_swap.get(model)
        if m is None:
            m = self._m_swap[model] = telemetry.counter(
                "fleet_swap_total",
                "Completed live checkpoint hot-swaps, by model",
                model=model)
        m.inc()
        telemetry.flight().record(
            "fleet", "swap_done", model=model, to_version=version,
            swapped=swapped, still_draining=len(still))
        return {"model": model, "version": version,
                "from_version": old_version, "swapped": swapped,
                "still_draining": still}

    def _resolve_factory(self, entry: _ModelEntry, *,
                         params: Any = None,
                         path: Optional[str] = None,
                         engine_factory=None):
        """Turn (params | path | engine_factory) into a zero-arg
        engine factory — the validation hot_swap always did, shared
        with the canary path."""
        if engine_factory is not None:
            return engine_factory
        if path is not None:
            from ... import checkpoint
            params = checkpoint.load_state(path)
        if params is None:
            raise ValueError(
                "hot_swap needs params=, path= or engine_factory=")
        base = entry.spec.engine_factory
        try:
            inspect.signature(base).bind_partial(params=params)
        except TypeError:
            raise ValueError(
                f"model {entry.spec.name!r}'s engine_factory does "
                f"not accept a params= keyword; hot-swap by "
                f"params/path requires a factory like "
                f"`lambda params=params0: ServeEngine(cfg, "
                f"params, ...)`") from None
        p = params
        return lambda p=p: base(params=p)

    def _swap_out(self, entry: _ModelEntry, targets,
                  drain_timeout_s: float):
        """Surge-then-drain ``targets`` out of the pool (one fresh
        replica spawned from the CURRENT factory per target, then the
        target drains — it finishes everything it accepted on the
        build that seated it). Returns ``(swapped, still_draining)``.
        Capacity never dips below the allocation; the transient +1
        replica shows in the arbiter's next ledger tick."""
        pool = entry.pool
        swapped = 0
        for r in targets:
            fresh = pool.spawn_replica()
            if fresh is None:
                raise GatewayClosed(
                    f"fleet pool {entry.spec.name!r} closed mid-swap")
            if pool.drain_replica(r):
                swapped += 1
        deadline = self._clock() + float(drain_timeout_s)
        still = []
        for r in targets:
            t = r._thread
            if t is not None:
                t.join(max(0.0, deadline - self._clock()))
                if t.is_alive():
                    still.append(r.name)
        return swapped, still

    # -- canary / promote / rollback (the flywheel's verbs) ------------------
    def canary_swap(self, model: str, *, params: Any = None,
                    path: Optional[str] = None,
                    engine_factory=None,
                    version: Optional[str] = None,
                    fraction: float = 0.25,
                    drain_timeout_s: float = 120.0) -> Dict[str, Any]:
        """Swap a candidate build into a bounded FRACTION of the pool
        (at least one replica) instead of all of it: the pool's
        factory/version move to the candidate, but only
        ``max(1, round(fraction * size))`` replicas are surged+drained
        — the rest keep serving the incumbent build, and ``route
        (version=)`` keeps in-flight requests on the build that seated
        them. The incumbent (factory, version) is recorded as the
        rollback anchor. NOTE: a supervisor respawn during the canary
        window comes up on the CANDIDATE build (the pool factory), so
        the canary fraction can only grow until promote/rollback
        settles it."""
        entry = self._entry(model)
        pool = entry.pool
        engine_factory = self._resolve_factory(
            entry, params=params, path=path,
            engine_factory=engine_factory)
        version = version or f"v{next(entry.swap_seq)}"
        old = pool.replicas()
        old_version = pool.version
        n = min(len(old), max(1, int(round(float(fraction)
                                           * len(old)))))
        entry.last_good = (pool._factory, old_version)
        pool.set_factory(engine_factory, version)
        entry.canary = {"version": version,
                        "from_version": old_version,
                        "replicas": n, "of": len(old)}
        telemetry.flight().record(
            "fleet", "canary_begin", model=model,
            from_version=old_version, to_version=version,
            canaries=n, pool=len(old))
        swapped, still = self._swap_out(entry, old[:n],
                                        drain_timeout_s)
        m = self._m_canary.get(model)
        if m is None:
            m = self._m_canary[model] = telemetry.counter(
                "fleet_canary_total",
                "Candidate builds canaried into a bounded fraction "
                "of a pool, by model", model=model)
        m.inc()
        return {"model": model, "version": version,
                "from_version": old_version, "canaries": n,
                "of": len(old), "swapped": swapped,
                "still_draining": still}

    def promote(self, model: str, *,
                drain_timeout_s: float = 120.0) -> Dict[str, Any]:
        """Finish a clean canary: surge+drain the REMAINING incumbent
        replicas onto the pool's current (candidate) build. The
        promoted build becomes the next rollback anchor."""
        entry = self._entry(model)
        pool = entry.pool
        version = pool.version
        targets = [r for r in pool.replicas()
                   if getattr(r, "version", None) != version]
        telemetry.flight().record(
            "fleet", "promote", model=model, to_version=version,
            remaining=len(targets))
        swapped, still = self._swap_out(entry, targets,
                                        drain_timeout_s)
        entry.canary = None
        entry.last_good = (pool._factory, version)
        m = self._m_swap.get(model)
        if m is None:
            m = self._m_swap[model] = telemetry.counter(
                "fleet_swap_total",
                "Completed live checkpoint hot-swaps, by model",
                model=model)
        m.inc()
        return {"model": model, "version": version,
                "swapped": swapped, "still_draining": still}

    def rollback(self, model: str, *, reason: str = "breach",
                 drain_timeout_s: float = 120.0) -> Dict[str, Any]:
        """The serve-side twin of the elastic trainer's loss-spike
        rollback: re-seat the pool on the LAST-GOOD build — every
        replica not already on it is surged+drained away, in-flight
        requests finish bit-identically on whichever build seated
        them. Counted in ``fleet_rollback_total{model,reason}`` and
        flight-recorded with the reason (the operator's first grep
        after a bad deploy)."""
        entry = self._entry(model)
        pool = entry.pool
        if entry.last_good is None:
            raise ValueError(
                f"model {model!r} has no last-good build recorded "
                f"(nothing was ever swapped); rollback is undefined")
        factory, version = entry.last_good
        bad_version = pool.version
        pool.set_factory(factory, version)
        targets = [r for r in pool.replicas()
                   if getattr(r, "version", None) != version]
        telemetry.flight().record(
            "fleet", "rollback_begin", model=model, reason=reason,
            from_version=bad_version, to_version=version,
            replicas=len(targets))
        swapped, still = self._swap_out(entry, targets,
                                        drain_timeout_s)
        entry.canary = None
        key = (model, reason)
        m = self._m_rollback.get(key)
        if m is None:
            m = self._m_rollback[key] = telemetry.counter(
                "fleet_rollback_total",
                "Serve-side rollbacks to the last-good build, by "
                "model and reason (slo_burn/anomaly/manual...)",
                model=model, reason=reason)
        m.inc()
        telemetry.flight().record(
            "fleet", "rollback_done", model=model, reason=reason,
            to_version=version, swapped=swapped,
            still_draining=len(still))
        return {"model": model, "version": version,
                "from_version": bad_version, "reason": reason,
                "swapped": swapped, "still_draining": still}

    # -- flywheel / training tenant ------------------------------------------
    def register_tenant(self, tenant, *,
                        chips: Optional[int] = None) -> None:
        """Register a non-serving arbiter tenant (the elastic
        trainer's :class:`~.arbiter.TrainingTenant`): its chips join
        the fleet budget and the arbiter lends/borrows between it and
        the pools. Requires the fleet to have been built with an
        arbiter policy."""
        if self.arbiter is None:
            raise ValueError(
                "this fleet has no arbiter (pass arbiter= to "
                "FleetGateway) — nothing would lend chips")
        self.arbiter.register(tenant.name, tenant, chips=chips)

    def attach_flywheel(self, model: str, controller) -> None:
        """Hang a :class:`~.flywheel.FlywheelController` off the fleet
        so ``/healthz``/``/state`` (and ``diagnose flywheel``) surface
        its phase, canary and decision history. Called by the
        controller's constructor."""
        self._entry(model)             # validate the name
        self._flywheels[model] = controller

    # -- observability -------------------------------------------------------
    def _update_goodput(self) -> None:
        """``mxtpu_goodput_ratio{loop="fleet"}``: the fraction of
        front-door traffic ADMITTED over the interval since the last
        scrape — the serving-tier analog of the train loops'
        useful-fraction (a shed request is wall time the fleet could
        not turn into tokens). Only written when the window saw
        traffic."""
        reg = telemetry.registry()
        acc = shed = 0.0
        for name in list(self._models):
            acc += reg.value("gateway_requests_total",
                             code="accepted", model=name)
            for code in ("429", "503"):
                shed += reg.value("gateway_requests_total",
                                  code=code, model=name)
        prev, self._prev_req = self._prev_req, (acc, shed)
        if prev is None:
            return
        da, ds = acc - prev[0], shed - prev[1]
        if da + ds > 0:
            self._g_goodput.set(da / (da + ds))

    def metrics_text(self) -> str:
        """GET /metrics: every model's series in one scrape (the
        per-model labels keep them apart), federated across peer
        processes when configured — the surface ``bench.py fleet``
        gates its acceptance on."""
        for entry in self._models.values():
            entry.gateway.refresh_gauges()
            if entry.gateway.slo is not None:
                entry.gateway.slo.tick()
        self._update_goodput()
        if self._federate:
            return dtrace.federate_text(
                telemetry.registry(), self._federate,
                process=telemetry.process_role(),
                secret=self._fed_secret)
        return telemetry.prometheus()

    def _health_causes(self, name: str,
                       h: Dict[str, Any]) -> List[str]:
        """Name WHY a model reads degraded (the aggregation a single
        /healthz probe needs to see a sick tenant): each cause is a
        stable token an alert can match on."""
        causes = []
        if h.get("tier", 0) > 0:
            causes.append(f"shed_tier_{h['tier']}")
        if h.get("healthy_replicas") == 0:
            causes.append("no_healthy_replicas")
        br = h.get("breaker")
        if br is not None and br.get("state") != "closed":
            causes.append("breaker_open")
        sup = h.get("supervisor")
        if sup:
            if sup.get("pending_spawns"):
                causes.append("replica_respawn_pending")
            if sup.get("restarts", 0) >= sup.get("max_restarts",
                                                 1 << 30):
                causes.append("supervisor_exhausted")
        slo = h.get("slo")
        if slo and slo.get("breached"):
            causes.append("slo_burn")
        fly = self._flywheels.get(name)
        if fly is not None:
            if getattr(fly, "rolling_back", False):
                causes.append("rollback_active")
            if getattr(fly, "halted", False):
                causes.append("flywheel_halted")
        return causes

    def health(self) -> Dict[str, Any]:
        """GET /healthz: per-model health blocks — each annotated with
        its degraded CAUSES (breaker open, supervisor exhausted, SLO
        burn, active rollback...) — plus the fleet verdict and the
        list of degraded models, so one probe sees a sick tenant
        without walking N per-model doors."""
        per = {}
        degraded = []
        for name, entry in self._models.items():
            h = entry.gateway.health()
            causes = self._health_causes(name, h)
            h["causes"] = causes
            if causes or h["status"] != "ok":
                h["status"] = "degraded"
                degraded.append(name)
            per[name] = h
        return {"ok": True,
                "status": "degraded" if degraded else "ok",
                "degraded": degraded,
                "models": per}

    def state(self) -> Dict[str, Any]:
        """GET /state: per-model topology (each model's full gateway
        state + version/chips/bounds + the last arbiter decision that
        touched it) and the arbiter ledger — what ``diagnose fleet``
        renders."""
        models = {}
        for name, entry in self._models.items():
            st = entry.gateway.state()
            st["version"] = entry.pool.version
            st["chips_per_replica"] = entry.pool.chips_per_replica
            st["min_replicas"] = entry.pool.min_replicas
            st["max_replicas"] = entry.pool.max_replicas
            st["arbiter_last"] = (self.arbiter.last_decision(name)
                                  if self.arbiter else None)
            st["canary"] = (dict(entry.canary)
                            if entry.canary else None)
            models[name] = st
        with self._aff_lock:
            sessions = len(self._affinity)
        return {"models": models,
                "arbiter": (self.arbiter.describe()
                            if self.arbiter else None),
                "flywheel": {name: fly.describe()
                             for name, fly
                             in self._flywheels.items()},
                "affinity_sessions": sessions}

    # -- lifecycle -----------------------------------------------------------
    def start_http(self, host: str = "127.0.0.1",
                   port: Optional[int] = None) -> int:
        """Bind + serve the EXISTING HTTP front door (frontdoor.py
        works against the four-method surface this class implements);
        returns the bound port."""
        from ..gateway.frontdoor import serve_http
        if port is None:
            port = env_int(
                "MXTPU_GATEWAY_PORT", 9300,
                "Default TCP port of the gateway HTTP front door.")
        self._http, bound = serve_http(self, host, port)
        return bound

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fly in list(self._flywheels.values()):
            try:
                fly.close()
            except Exception:
                pass
        if self._arbiter_stop is not None:
            self._arbiter_stop.set()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        for entry in self._models.values():
            entry.gateway.close()
