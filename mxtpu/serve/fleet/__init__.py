"""Fleet control plane: multi-model, multi-tenant serving over the
gateway — named-model routing, SLO-driven chip arbitration between
per-model pools, priority classes, live checkpoint hot-swap, and
session affinity. See docs/serving.md §"Fleet control plane"."""
from .arbiter import ArbiterPolicy, FleetArbiter
from .fleet import FleetGateway, FleetPool, ModelSpec

__all__ = ["ArbiterPolicy", "FleetArbiter", "FleetGateway",
           "FleetPool", "ModelSpec"]
