"""Fleet control plane: multi-model, multi-tenant serving over the
gateway — named-model routing, SLO-driven chip arbitration between
per-model pools, priority classes, live checkpoint hot-swap, session
affinity, and the train→serve deployment flywheel (publish → canary →
promote/auto-rollback with chip lending). See docs/serving.md §"Fleet
control plane" and docs/robustness.md §"Continuous deployment"."""
from .arbiter import ArbiterPolicy, FleetArbiter, TrainingTenant
from .fleet import FleetGateway, FleetPool, ModelSpec
from .flywheel import FlywheelController

__all__ = ["ArbiterPolicy", "FleetArbiter", "FleetGateway",
           "FleetPool", "FlywheelController", "ModelSpec",
           "TrainingTenant"]
