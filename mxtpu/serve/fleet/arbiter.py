"""SLO-driven chip arbitration across per-model pools: ONE allocator
for the whole fleet, replacing per-model autoscaling.

A per-model autoscaler sees only its own queue and p99 — two
autoscalers on one chip budget either both hold their maximum
(stranding chips on the cold model) or fight over the free pool. The
arbiter reads every pool's signals TOGETHER each tick and moves whole
replicas' worth of chips between them (the AlpaServe observation:
cross-model placement on a shared budget is where utilization is won):

- a pool is HOT when its queue pressure exceeds ``pressure_high`` or
  its SLO burn rate exceeds ``burn_high`` (the PR 8 ``SLOTracker``
  burn, read per model — the tracker itself does the windowing);
- a pool is a DONOR when it has been sustained-idle (empty queue, low
  occupancy) for ``idle_s`` and sits above its ``min_replicas``;
- each tick grants at most ONE replica to the hottest pool — from the
  free budget if any, else by shrinking the coldest donor first (the
  chip MOVE the fleet bench asserts); with no claimant, one
  sustained-idle pool shrinks to return chips to the free budget.

Hysteresis is the autoscaler's (deliberately boring) discipline
reused fleet-wide: per-model cooldowns between decisions, sustained
idle before donating, one replica per tick. Every decision increments
``fleet_scale_events_total{model,direction}`` and lands in the flight
recorder with the signals that drove it; ``fleet_chips_in_use{model}``
/ ``fleet_chips_free`` are the live ledger. The loop is a pure
function of (clock, signals): tests inject both and single-step
:meth:`FleetArbiter.tick`.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ... import telemetry
from ..gateway.replica import GatewayClosed

__all__ = ["ArbiterPolicy", "FleetArbiter", "TrainingTenant"]


@dataclass
class ArbiterPolicy:
    chip_budget: int = 0          # 0 = derived: the fleet's initial
    #                               allocation (sum of replicas*chips)
    interval_s: float = 1.0       # loop period
    cooldown_s: float = 10.0      # per-model gap between decisions
    pressure_high: float = 2.0    # un-seated requests per replica
    burn_high: float = 1.0        # SLO burn rate over = hot
    occupancy_low: float = 0.25   # idle ceiling (donor eligibility)
    idle_s: Optional[float] = None   # sustained idle before donating;
    #                                  None = cooldown_s

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, "
                             f"got {self.interval_s}")
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, "
                             f"got {self.cooldown_s}")


class FleetArbiter:
    """Arbitrates ``policy.chip_budget`` chips between the fleet's
    pools. ``entries`` is the fleet's LIVE ``{name: entry}`` mapping
    (each entry carries ``.pool`` — size, bounds, chips_per_replica,
    scale_to — and ``.gateway`` — whose ``slo`` tracker supplies the
    burn rate); reading it live means models registered after
    construction are arbitrated too.

    ``signals``: optional ``fn(name, entry) -> {"pressure",
    "occupancy", "burn", "queued", "size"}`` override — the
    deterministic-test hook (synthetic burn without real latency)."""

    def __init__(self, entries: Dict[str, Any], policy: ArbiterPolicy,
                 *, clock: Optional[Callable[[], float]] = None,
                 signals: Optional[Callable[[str, Any],
                                            Dict[str, float]]] = None):
        self.entries = entries
        self.policy = policy
        self._clock = clock or time.monotonic
        self._signals_override = signals
        # non-serving tenants (the elastic trainer's TrainingTenant)
        # registered after construction live here, NOT in the fleet's
        # model mapping — fleet iteration (health/state/metrics) never
        # sees them, arbitration always does
        self._tenants: Dict[str, Any] = {}
        self.budget = int(policy.chip_budget) if policy.chip_budget \
            else sum(e.pool.size * self._cpr(n, e)
                     for n, e in entries.items())
        self._idle_since: Dict[str, float] = {}
        self._last_scale: Dict[str, float] = {}
        self._m_events: Dict[tuple, Any] = {}
        self._m_chips: Dict[str, Any] = {}
        self._m_free = telemetry.gauge(
            "fleet_chips_free",
            "Chips of the fleet budget not allocated to any pool")
        self.decisions: List[Dict[str, Any]] = []   # bounded: tick()

    def _get(self, name: str):
        entry = self.entries.get(name)
        return entry if entry is not None else self._tenants.get(name)

    def _items(self):
        """Live arbitration view: the fleet's model entries plus
        registered tenants."""
        out = list(self.entries.items())
        out.extend(self._tenants.items())
        return out

    def register(self, name: str, tenant: Any, *,
                 chips: Optional[int] = None) -> None:
        """Register a non-serving tenant (e.g. :class:`TrainingTenant`
        wrapping the elastic mesh) as a claimant/donor. Its current
        allocation joins the budget — chips it later yields become
        free budget the pools can claim, and vice versa. Pass
        ``chips`` to add a different amount (0 = the budget already
        counted them)."""
        if name in self.entries or name in self._tenants:
            raise ValueError(f"arbiter already has a tenant {name!r}")
        self._tenants[name] = tenant
        add = int(chips) if chips is not None \
            else tenant.pool.size * self._cpr(name, tenant)
        self.budget += add
        telemetry.flight().record(
            "fleet", "tenant_register", tenant=name, chips=add,
            budget=self.budget)

    def _cpr(self, name: str, entry: Any = None) -> int:
        entry = entry if entry is not None else self._get(name)
        return int(getattr(entry.pool, "chips_per_replica", 1)
                   if entry is not None else 1)

    def _bounds(self, name: str) -> tuple:
        pool = self._get(name).pool
        return (int(getattr(pool, "min_replicas", 1)),
                int(getattr(pool, "max_replicas", 1 << 30)))

    def _signals(self, name: str, entry) -> Dict[str, float]:
        """Default signal read: pool load at the source (the same
        numbers the autoscaler used) + the model's SLO burn rate.
        ``slo.tick()`` is rate-limited to its own window, so arbiter
        cadence cannot chop the burn computation into noise. An entry
        that carries its own ``signals()`` (a tenant) speaks for
        itself."""
        custom = getattr(entry, "signals", None)
        if custom is not None:
            return custom()
        pool = entry.pool
        load = pool.load_total()
        n = pool.size
        burn = 0.0
        slo = getattr(entry.gateway, "slo", None)
        if slo is not None:
            snap = slo.tick()
            burns = [v.get("burn") for v in snap.values()
                     if v.get("burn") is not None]
            if burns:
                burn = max(burns)
        return {"pressure": load["queued"] / max(1, n),
                "occupancy": load["active"] / max(1, load["slots"]),
                "queued": float(load["queued"]),
                "size": float(n), "burn": float(burn)}

    def _count_event(self, model: str, direction: str) -> None:
        key = (model, direction)
        m = self._m_events.get(key)
        if m is None:
            m = self._m_events[key] = telemetry.counter(
                "fleet_scale_events_total",
                "Fleet arbiter decisions, by model and direction",
                model=model, direction=direction)
        m.inc()

    def _scale(self, name: str, delta: int, now: float, *,
               reason: str,
               sigs: Dict[str, Dict[str, float]]
               ) -> Optional[Dict[str, Any]]:
        entry = self._get(name)
        if entry is None:
            return None
        n = entry.pool.size
        try:
            entry.pool.scale_to(n + delta)
        except GatewayClosed:
            # a tick racing fleet shutdown: the pool refused loudly —
            # stand down, record nothing
            return None
        direction = "up" if delta > 0 else "down"
        self._last_scale[name] = now
        self._idle_since.pop(name, None)
        self._count_event(name, direction)
        s = sigs.get(name, {})
        record = {"t": now, "model": name, "direction": direction,
                  "from": n, "to": n + delta, "reason": reason,
                  "pressure": round(s.get("pressure", 0.0), 3),
                  "occupancy": round(s.get("occupancy", 0.0), 3),
                  "burn": round(s.get("burn", 0.0), 3)}
        telemetry.flight().record("fleet", "scale", **record)
        self.decisions.append(record)
        del self.decisions[:-64]
        return record

    def tick(self) -> List[Dict[str, Any]]:
        """One arbitration pass; returns the decisions made (possibly
        a down on a donor AND an up on the claimant — the chip
        move)."""
        pol = self.policy
        now = self._clock()
        sigs: Dict[str, Dict[str, float]] = {}
        for name, entry in self._items():
            try:
                sigs[name] = (
                    self._signals_override(name, entry)
                    if self._signals_override is not None
                    else self._signals(name, entry))
            except GatewayClosed:
                continue
        # idle bookkeeping (donor eligibility needs SUSTAINED idle —
        # one quiet tick between bursts must not donate a replica)
        for name, s in sigs.items():
            hot_sig = (s["pressure"] > pol.pressure_high
                       or s["burn"] > pol.burn_high)
            if (not hot_sig and s["queued"] == 0
                    and s["occupancy"] < pol.occupancy_low):
                self._idle_since.setdefault(name, now)
            else:
                self._idle_since.pop(name, None)
        idle_need = (pol.idle_s if pol.idle_s is not None
                     else pol.cooldown_s)

        def in_cooldown(name: str) -> bool:
            t = self._last_scale.get(name)
            return t is not None and now - t < pol.cooldown_s

        hot = sorted(
            (n for n, s in sigs.items()
             if (s["pressure"] > pol.pressure_high
                 or s["burn"] > pol.burn_high)
             and s["size"] < self._bounds(n)[1]
             and not in_cooldown(n)),
            key=lambda n: (sigs[n]["burn"], sigs[n]["pressure"]),
            reverse=True)
        donors = sorted(
            (n for n, s in sigs.items()
             if s["size"] > self._bounds(n)[0]
             and not in_cooldown(n)
             and n in self._idle_since
             and now - self._idle_since[n] >= idle_need),
            key=lambda n: (sigs[n]["pressure"], sigs[n]["occupancy"]))

        used = sum(int(s["size"]) * self._cpr(n)
                   for n, s in sigs.items())
        free = self.budget - used
        decisions: List[Dict[str, Any]] = []
        if hot:
            name = hot[0]
            need = self._cpr(name)
            for donor in (d for d in donors if d != name):
                if free >= need:
                    break
                d = self._scale(donor, -1, now,
                                reason=f"yield->{name}", sigs=sigs)
                if d is not None:
                    decisions.append(d)
                    free += self._cpr(donor)
            if free < need:
                # still short: PREEMPTIBLE tenants (the training mesh)
                # yield under serve load without waiting for sustained
                # idle — training time is the fleet's reserve capacity
                for donor in (
                        d for d, s in sigs.items()
                        if d != name and d not in donors
                        and getattr(self._get(d), "preemptible", False)
                        and s["size"] > self._bounds(d)[0]
                        and not in_cooldown(d)):
                    if free >= need:
                        break
                    d = self._scale(donor, -1, now,
                                    reason=f"preempt->{name}",
                                    sigs=sigs)
                    if d is not None:
                        decisions.append(d)
                        free += self._cpr(donor)
            if free >= need:
                d = self._scale(name, +1, now, reason="hot",
                                sigs=sigs)
                if d is not None:
                    decisions.append(d)
        elif donors:
            # nothing is burning: return ONE sustained-idle replica's
            # chips to the free budget (the next hot tick grants them
            # without waiting on a donor's cooldown)
            d = self._scale(donors[0], -1, now, reason="idle",
                            sigs=sigs)
            if d is not None:
                decisions.append(d)

        # live chip ledger (post-decision sizes)
        used = 0
        for name, entry in self._items():
            chips = entry.pool.size * self._cpr(name)
            used += chips
            g = self._m_chips.get(name)
            if g is None:
                g = self._m_chips[name] = telemetry.gauge(
                    "fleet_chips_in_use",
                    "Chips currently allocated to the model's pool",
                    model=name)
            g.set(chips)
        self._m_free.set(max(0, self.budget - used))
        return decisions

    def last_decision(self, model: str) -> Optional[Dict[str, Any]]:
        """Most recent decision touching ``model`` (diagnose's 'last
        arbiter decision' column; None before the first)."""
        for d in reversed(self.decisions):
            if d["model"] == model:
                return dict(d)
        return None

    def describe(self) -> Dict[str, Any]:
        """Live budget + per-pool chips + recent decisions
        (GET /state)."""
        chips = {}
        for name, entry in self._items():
            try:
                chips[name] = entry.pool.size * self._cpr(name, entry)
            except Exception:
                continue
        return {"budget": self.budget, "chips": chips,
                "free": max(0, self.budget - sum(chips.values())),
                "cooldown_s": self.policy.cooldown_s,
                "decisions": self.decisions[-8:]}

    def run_forever(self, stop: threading.Event) -> None:
        while not stop.wait(self.policy.interval_s):
            try:
                self.tick()
            except Exception:
                # arbitration must never die quietly; the flight ring
                # has the event, the next tick retries
                telemetry.flight().record("fleet", "arbiter_error")


class TrainingTenant:
    """The TRAINING side as an arbiter tenant: register one of these
    (``FleetArbiter.register`` / ``FleetGateway.register_tenant``) and
    the elastic mesh joins fleet chip arbitration as claimant AND
    donor — serving reclaims chips under load, training borrows idle
    chips back (docs/robustness.md §"Continuous deployment").

    ``resize(chips, reason)`` is the callback into the training side —
    typically ``ElasticTrainer.request_world`` — invoked from the
    arbiter tick thread, so it must only REQUEST the change (the
    trainer applies it at its next step boundary via the
    generation-bump rebuild). One tenant "replica" is one chip.

    Semantics, in arbiter terms: below ``want`` chips the tenant
    reports hot (pressure ``hunger_pressure``) and claims from the
    free budget; at or above ``want`` it reports idle, so chips over
    ``want`` drain back. Its burn is always 0, so any pool with real
    SLO burn outranks it. It is ``preemptible``: when a pool is hot
    and no idle donor covers the need, the arbiter shrinks the tenant
    immediately — training never blocks serving on "sustained idle"
    it will never exhibit."""

    preemptible = True
    gateway = None                    # no SLO: burn reads as 0
    chips_per_replica = 1

    def __init__(self, resize: Callable[[int, str], None], *,
                 chips: int, want: Optional[int] = None,
                 min_chips: int = 1, max_chips: Optional[int] = None,
                 name: str = "train", hunger_pressure: float = 2.5):
        self.name = name
        self._resize = resize
        self.size = int(chips)
        self.want = int(want if want is not None else chips)
        self.min_replicas = int(min_chips)
        self.max_replicas = int(max_chips if max_chips is not None
                                else max(self.size, self.want))
        self.hunger_pressure = float(hunger_pressure)
        self.pool = self              # entry.pool protocol: itself
        self._m_lends: Dict[str, Any] = {}

    def signals(self) -> Dict[str, float]:
        hungry = self.size < self.want
        return {
            "pressure": self.hunger_pressure if hungry else 0.0,
            # never "sustained idle" at/below want: the idle-donation
            # path would strip a chip the tenant immediately re-claims
            # (an arbiter-powered oscillation); only surplus over
            # `want` reads as idle and drains back
            "occupancy": 0.0 if self.size > self.want else 1.0,
            "queued": float(max(0, self.want - self.size)),
            "size": float(self.size), "burn": 0.0}

    def load_total(self) -> Dict[str, int]:
        # only reached when a signals override bypasses signals()
        return {"queued": max(0, self.want - self.size),
                "active": min(self.size, self.want),
                "slots": max(1, self.size)}

    def scale_to(self, n: int) -> None:
        n = max(self.min_replicas, min(int(n), self.max_replicas))
        if n == self.size:
            return
        direction = "borrow" if n > self.size else "lend"
        m = self._m_lends.get(direction)
        if m is None:
            m = self._m_lends[direction] = telemetry.counter(
                "fleet_chip_lends_total",
                "Chips moved between the training tenant and the "
                "serving budget by the arbiter (lend = training "
                "yields to serving, borrow = training reclaims).",
                tenant=self.name, direction=direction)
        m.inc(abs(n - self.size))
        telemetry.flight().record(
            "fleet", "tenant_resize", tenant=self.name,
            chips_from=self.size, chips_to=n, direction=direction)
        # optimistic: the ledger reads the granted size now; the
        # trainer applies it at its next step boundary
        self.size = n
        self._resize(n, f"arbiter-{direction}")
