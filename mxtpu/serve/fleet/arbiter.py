"""SLO-driven chip arbitration across per-model pools: ONE allocator
for the whole fleet, replacing per-model autoscaling.

A per-model autoscaler sees only its own queue and p99 — two
autoscalers on one chip budget either both hold their maximum
(stranding chips on the cold model) or fight over the free pool. The
arbiter reads every pool's signals TOGETHER each tick and moves whole
replicas' worth of chips between them (the AlpaServe observation:
cross-model placement on a shared budget is where utilization is won):

- a pool is HOT when its queue pressure exceeds ``pressure_high`` or
  its SLO burn rate exceeds ``burn_high`` (the PR 8 ``SLOTracker``
  burn, read per model — the tracker itself does the windowing);
- a pool is a DONOR when it has been sustained-idle (empty queue, low
  occupancy) for ``idle_s`` and sits above its ``min_replicas``;
- each tick grants at most ONE replica to the hottest pool — from the
  free budget if any, else by shrinking the coldest donor first (the
  chip MOVE the fleet bench asserts); with no claimant, one
  sustained-idle pool shrinks to return chips to the free budget.

Hysteresis is the autoscaler's (deliberately boring) discipline
reused fleet-wide: per-model cooldowns between decisions, sustained
idle before donating, one replica per tick. Every decision increments
``fleet_scale_events_total{model,direction}`` and lands in the flight
recorder with the signals that drove it; ``fleet_chips_in_use{model}``
/ ``fleet_chips_free`` are the live ledger. The loop is a pure
function of (clock, signals): tests inject both and single-step
:meth:`FleetArbiter.tick`.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ... import telemetry
from ..gateway.replica import GatewayClosed

__all__ = ["ArbiterPolicy", "FleetArbiter"]


@dataclass
class ArbiterPolicy:
    chip_budget: int = 0          # 0 = derived: the fleet's initial
    #                               allocation (sum of replicas*chips)
    interval_s: float = 1.0       # loop period
    cooldown_s: float = 10.0      # per-model gap between decisions
    pressure_high: float = 2.0    # un-seated requests per replica
    burn_high: float = 1.0        # SLO burn rate over = hot
    occupancy_low: float = 0.25   # idle ceiling (donor eligibility)
    idle_s: Optional[float] = None   # sustained idle before donating;
    #                                  None = cooldown_s

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, "
                             f"got {self.interval_s}")
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, "
                             f"got {self.cooldown_s}")


class FleetArbiter:
    """Arbitrates ``policy.chip_budget`` chips between the fleet's
    pools. ``entries`` is the fleet's LIVE ``{name: entry}`` mapping
    (each entry carries ``.pool`` — size, bounds, chips_per_replica,
    scale_to — and ``.gateway`` — whose ``slo`` tracker supplies the
    burn rate); reading it live means models registered after
    construction are arbitrated too.

    ``signals``: optional ``fn(name, entry) -> {"pressure",
    "occupancy", "burn", "queued", "size"}`` override — the
    deterministic-test hook (synthetic burn without real latency)."""

    def __init__(self, entries: Dict[str, Any], policy: ArbiterPolicy,
                 *, clock: Optional[Callable[[], float]] = None,
                 signals: Optional[Callable[[str, Any],
                                            Dict[str, float]]] = None):
        self.entries = entries
        self.policy = policy
        self._clock = clock or time.monotonic
        self._signals_override = signals
        self.budget = int(policy.chip_budget) if policy.chip_budget \
            else sum(e.pool.size * self._cpr(n)
                     for n, e in entries.items())
        self._idle_since: Dict[str, float] = {}
        self._last_scale: Dict[str, float] = {}
        self._m_events: Dict[tuple, Any] = {}
        self._m_chips: Dict[str, Any] = {}
        self._m_free = telemetry.gauge(
            "fleet_chips_free",
            "Chips of the fleet budget not allocated to any pool")
        self.decisions: List[Dict[str, Any]] = []   # bounded: tick()

    def _cpr(self, name: str) -> int:
        entry = self.entries.get(name)
        return int(getattr(entry.pool, "chips_per_replica", 1)
                   if entry is not None else 1)

    def _bounds(self, name: str) -> tuple:
        pool = self.entries[name].pool
        return (int(getattr(pool, "min_replicas", 1)),
                int(getattr(pool, "max_replicas", 1 << 30)))

    def _signals(self, name: str, entry) -> Dict[str, float]:
        """Default signal read: pool load at the source (the same
        numbers the autoscaler used) + the model's SLO burn rate.
        ``slo.tick()`` is rate-limited to its own window, so arbiter
        cadence cannot chop the burn computation into noise."""
        pool = entry.pool
        load = pool.load_total()
        n = pool.size
        burn = 0.0
        slo = getattr(entry.gateway, "slo", None)
        if slo is not None:
            snap = slo.tick()
            burns = [v.get("burn") for v in snap.values()
                     if v.get("burn") is not None]
            if burns:
                burn = max(burns)
        return {"pressure": load["queued"] / max(1, n),
                "occupancy": load["active"] / max(1, load["slots"]),
                "queued": float(load["queued"]),
                "size": float(n), "burn": float(burn)}

    def _count_event(self, model: str, direction: str) -> None:
        key = (model, direction)
        m = self._m_events.get(key)
        if m is None:
            m = self._m_events[key] = telemetry.counter(
                "fleet_scale_events_total",
                "Fleet arbiter decisions, by model and direction",
                model=model, direction=direction)
        m.inc()

    def _scale(self, name: str, delta: int, now: float, *,
               reason: str,
               sigs: Dict[str, Dict[str, float]]
               ) -> Optional[Dict[str, Any]]:
        entry = self.entries.get(name)
        if entry is None:
            return None
        n = entry.pool.size
        try:
            entry.pool.scale_to(n + delta)
        except GatewayClosed:
            # a tick racing fleet shutdown: the pool refused loudly —
            # stand down, record nothing
            return None
        direction = "up" if delta > 0 else "down"
        self._last_scale[name] = now
        self._idle_since.pop(name, None)
        self._count_event(name, direction)
        s = sigs.get(name, {})
        record = {"t": now, "model": name, "direction": direction,
                  "from": n, "to": n + delta, "reason": reason,
                  "pressure": round(s.get("pressure", 0.0), 3),
                  "occupancy": round(s.get("occupancy", 0.0), 3),
                  "burn": round(s.get("burn", 0.0), 3)}
        telemetry.flight().record("fleet", "scale", **record)
        self.decisions.append(record)
        del self.decisions[:-64]
        return record

    def tick(self) -> List[Dict[str, Any]]:
        """One arbitration pass; returns the decisions made (possibly
        a down on a donor AND an up on the claimant — the chip
        move)."""
        pol = self.policy
        now = self._clock()
        sigs: Dict[str, Dict[str, float]] = {}
        for name, entry in list(self.entries.items()):
            try:
                sigs[name] = (
                    self._signals_override(name, entry)
                    if self._signals_override is not None
                    else self._signals(name, entry))
            except GatewayClosed:
                continue
        # idle bookkeeping (donor eligibility needs SUSTAINED idle —
        # one quiet tick between bursts must not donate a replica)
        for name, s in sigs.items():
            hot_sig = (s["pressure"] > pol.pressure_high
                       or s["burn"] > pol.burn_high)
            if (not hot_sig and s["queued"] == 0
                    and s["occupancy"] < pol.occupancy_low):
                self._idle_since.setdefault(name, now)
            else:
                self._idle_since.pop(name, None)
        idle_need = (pol.idle_s if pol.idle_s is not None
                     else pol.cooldown_s)

        def in_cooldown(name: str) -> bool:
            t = self._last_scale.get(name)
            return t is not None and now - t < pol.cooldown_s

        hot = sorted(
            (n for n, s in sigs.items()
             if (s["pressure"] > pol.pressure_high
                 or s["burn"] > pol.burn_high)
             and s["size"] < self._bounds(n)[1]
             and not in_cooldown(n)),
            key=lambda n: (sigs[n]["burn"], sigs[n]["pressure"]),
            reverse=True)
        donors = sorted(
            (n for n, s in sigs.items()
             if s["size"] > self._bounds(n)[0]
             and not in_cooldown(n)
             and n in self._idle_since
             and now - self._idle_since[n] >= idle_need),
            key=lambda n: (sigs[n]["pressure"], sigs[n]["occupancy"]))

        used = sum(int(s["size"]) * self._cpr(n)
                   for n, s in sigs.items())
        free = self.budget - used
        decisions: List[Dict[str, Any]] = []
        if hot:
            name = hot[0]
            need = self._cpr(name)
            for donor in (d for d in donors if d != name):
                if free >= need:
                    break
                d = self._scale(donor, -1, now,
                                reason=f"yield->{name}", sigs=sigs)
                if d is not None:
                    decisions.append(d)
                    free += self._cpr(donor)
            if free >= need:
                d = self._scale(name, +1, now, reason="hot",
                                sigs=sigs)
                if d is not None:
                    decisions.append(d)
        elif donors:
            # nothing is burning: return ONE sustained-idle replica's
            # chips to the free budget (the next hot tick grants them
            # without waiting on a donor's cooldown)
            d = self._scale(donors[0], -1, now, reason="idle",
                            sigs=sigs)
            if d is not None:
                decisions.append(d)

        # live chip ledger (post-decision sizes)
        used = 0
        for name, entry in list(self.entries.items()):
            chips = entry.pool.size * self._cpr(name)
            used += chips
            g = self._m_chips.get(name)
            if g is None:
                g = self._m_chips[name] = telemetry.gauge(
                    "fleet_chips_in_use",
                    "Chips currently allocated to the model's pool",
                    model=name)
            g.set(chips)
        self._m_free.set(max(0, self.budget - used))
        return decisions

    def last_decision(self, model: str) -> Optional[Dict[str, Any]]:
        """Most recent decision touching ``model`` (diagnose's 'last
        arbiter decision' column; None before the first)."""
        for d in reversed(self.decisions):
            if d["model"] == model:
                return dict(d)
        return None

    def describe(self) -> Dict[str, Any]:
        """Live budget + per-pool chips + recent decisions
        (GET /state)."""
        chips = {}
        for name in list(self.entries):
            try:
                chips[name] = self.entries[name].pool.size \
                    * self._cpr(name)
            except KeyError:
                continue
        return {"budget": self.budget, "chips": chips,
                "free": max(0, self.budget - sum(chips.values())),
                "cooldown_s": self.policy.cooldown_s,
                "decisions": self.decisions[-8:]}

    def run_forever(self, stop: threading.Event) -> None:
        while not stop.wait(self.policy.interval_s):
            try:
                self.tick()
            except Exception:
                # arbitration must never die quietly; the flight ring
                # has the event, the next tick retries
                telemetry.flight().record("fleet", "arbiter_error")
