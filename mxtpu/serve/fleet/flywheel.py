"""Flywheel: the continuous train→serve deployment loop
(docs/robustness.md §"Continuous deployment").

The elastic trainer publishes manifest-committed checkpoints on a
cadence (``CheckpointManager.publish`` → the ``latest-published``
pointer); a :class:`FlywheelController` on the serve side subscribes
to that pointer and closes the loop:

    publish → eval gate → canary (bounded fraction of one pool,
    per-version SLO burn split) → hold window → promote fleet-wide
                                 ↘ burn breach / anomaly spike →
                                   auto-rollback to last-good

Every stage is built from seams that already survive chaos: the
pointer validates like the PR 11 data journal (a torn publish reads
as "nothing new"), the canary uses the fleet's surge-then-drain swap
(zero accepted requests dropped, ``route(version=)`` keeps in-flight
requests bit-identical to the build that seated them), and rollback
is the serve-side twin of the trainer's loss-spike rollback — bounded
budget, ``fleet_rollback_total{model,reason}``, flight records. A
spent budget HALTS deployment (no new canaries) while the last-good
build keeps serving: persistent bad candidates are a bug upstream,
not weather.

The controller is a pure function of (clock, pointer, burn signals):
tests inject the clock and single-step :meth:`FlywheelController
.tick`; production calls :meth:`start` for the background thread.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ... import telemetry
from ...base import ManifestError, env_float, env_int
from ...telemetry import distributed as dtrace

__all__ = ["FlywheelController"]


class FlywheelController:
    """Watches a checkpoint directory's ``latest-published`` pointer
    and deploys candidates into ``fleet``'s ``model`` pool through
    canary → promote/rollback.

    ``load_candidate(pointer) -> params`` turns a pointer record
    (``step``/``seq``/publisher metadata) into a weight pytree for
    the pool's engine factory — typically a
    ``CheckpointManager.restore(step, ...)`` plus whatever export the
    serving weights need. It MUST raise on a torn/partial candidate
    (orbax validation does this for free): the candidate is then
    rejected and counted, and live traffic is never touched.

    ``eval_gate(pointer, params) -> bool`` (optional) vetoes a
    candidate before any replica changes — the configurable offline
    eval. A gate that raises counts as a veto, loudly.

    Burn gating reads the per-version TTFT split
    (``Gateway.version_ttft``): one
    :class:`~mxtpu.telemetry.distributed.SLOTracker` per live build,
    compared against ``burn_high``; a Perfscope step-anomaly delta
    above ``anomaly_budget`` during the canary window is the second
    tripwire. ``slo`` defaults to the model's :class:`~.fleet
    .ModelSpec` targets; without targets, burn gating is off and only
    anomalies/hold-ticks govern."""

    def __init__(self, fleet, model: str, directory: str, *,
                 load_candidate: Callable[[Dict[str, Any]], Any],
                 eval_gate: Optional[Callable[..., bool]] = None,
                 canary_fraction: Optional[float] = None,
                 hold_ticks: Optional[int] = None,
                 burn_high: Optional[float] = None,
                 max_rollbacks: Optional[int] = None,
                 anomaly_budget: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 slo: Optional[Dict[str, float]] = None,
                 drain_timeout_s: float = 120.0,
                 clock: Optional[Callable[[], float]] = None):
        self.fleet = fleet
        self.model = model
        self.directory = directory
        self.load_candidate = load_candidate
        self.eval_gate = eval_gate
        self._clock = clock or time.monotonic
        self.fraction = canary_fraction if canary_fraction is not None \
            else env_float(
                "MXTPU_FLYWHEEL_CANARY_FRACTION", 0.25,
                "Flywheel: fraction of a model's pool (>= 1 replica) "
                "a candidate build canaries into before promotion.")
        self.hold_ticks = hold_ticks if hold_ticks is not None \
            else env_int(
                "MXTPU_FLYWHEEL_HOLD_TICKS", 3,
                "Flywheel: consecutive clean controller ticks a "
                "canary must hold before fleet-wide promotion.")
        self.burn_high = burn_high if burn_high is not None \
            else env_float(
                "MXTPU_FLYWHEEL_BURN_HIGH", 1.0,
                "Flywheel: canary-version SLO burn rate above this "
                "triggers auto-rollback to the last-good build.")
        self.max_rollbacks = max_rollbacks if max_rollbacks is not None \
            else env_int(
                "MXTPU_FLYWHEEL_MAX_ROLLBACKS", 2,
                "Flywheel: auto-rollback budget per controller; once "
                "spent the flywheel HALTS (no new canaries) while "
                "the last-good build keeps serving.")
        self.anomaly_budget = anomaly_budget \
            if anomaly_budget is not None else env_int(
                "MXTPU_FLYWHEEL_ANOMALY_BUDGET", 2,
                "Flywheel: Perfscope step anomalies tolerated during "
                "one canary window before auto-rollback.")
        self.poll_s = poll_s if poll_s is not None else env_float(
            "MXTPU_FLYWHEEL_POLL_S", 2.0,
            "Flywheel: background controller tick period (pointer "
            "poll + canary burn assessment).")
        self.drain_timeout_s = float(drain_timeout_s)
        entry = fleet._entry(model)
        self._slo_spec = slo if slo is not None else entry.spec.slo
        self.phase = "idle"            # idle | canary
        self.halted = False
        self.rolling_back = False
        self.seen_seq = -1             # highest pointer seq processed
        self.rollbacks = 0
        self.canary: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []   # bounded: _note()
        self._trackers: Dict[str, Any] = {}
        self._anom0 = 0.0
        self._m_cand: Dict[str, Any] = {}
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        fleet.attach_flywheel(model, self)

    # -- bookkeeping ---------------------------------------------------------
    def _count(self, result: str) -> None:
        m = self._m_cand.get(result)
        if m is None:
            m = self._m_cand[result] = telemetry.counter(
                "fleet_candidates_total",
                "Published candidates by flywheel outcome (canaried/"
                "promoted/rolled_back/rejected_torn/rejected_gate/"
                "torn_pointer).", model=self.model, result=result)
        m.inc()

    def _note(self, action: str, **kw) -> Dict[str, Any]:
        rec = dict(kw, t=self._clock(), action=action,
                   model=self.model)
        telemetry.flight().record("flywheel", action, **{
            k: v for k, v in rec.items() if k != "action"})
        self.history.append(rec)
        del self.history[:-32]
        return rec

    def _anomaly_total(self) -> float:
        """Fleet-wide Perfscope step-anomaly count (summed over
        programs) — the canary window compares deltas against
        ``anomaly_budget``."""
        samples = dtrace.parse_prometheus(
            telemetry.prometheus())["samples"]
        return sum(v for (name, _), v in samples.items()
                   if name == "mxtpu_step_anomalies_total")

    def _poll_pointer(self) -> Optional[Dict[str, Any]]:
        from ... import checkpoint
        try:
            return checkpoint.read_published(self.directory)
        except ManifestError as e:
            # torn mid-publish (a kill beat the manifest commit):
            # skipped exactly like a torn journal — the incumbent
            # keeps serving, the next publish supersedes
            self._count("torn_pointer")
            self._note("torn_pointer", error=str(e))
            return None

    # -- the control loop ----------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """One controller pass; returns the decisions made. Idle:
        poll the pointer, gate + canary a new candidate. Canary:
        assess per-version burn + anomaly delta, then promote on a
        clean hold window or roll back on a breach."""
        out: List[Dict[str, Any]] = []
        if self.phase == "idle":
            if self.halted:
                return out
            ptr = self._poll_pointer()
            if ptr is not None and int(ptr.get("seq", 0)) \
                    > self.seen_seq:
                self.seen_seq = int(ptr["seq"])
                out.extend(self._consider(ptr))
        elif self.phase == "canary":
            out.extend(self._assess())
        return out

    def _consider(self, ptr: Dict[str, Any]) -> List[Dict[str, Any]]:
        step, seq = int(ptr["step"]), int(ptr["seq"])
        try:
            params = self.load_candidate(ptr)
        except Exception as e:
            # torn/partial candidate: the pointer committed but the
            # checkpoint it names did not survive — reject WITHOUT
            # touching live traffic
            self._count("rejected_torn")
            return [self._note("candidate_rejected", step=step,
                               seq=seq, reason="torn",
                               error=f"{type(e).__name__}: {e}")]
        if self.eval_gate is not None:
            try:
                ok = bool(self.eval_gate(ptr, params))
            except Exception as e:
                ok = False
                self._note("gate_error", step=step, seq=seq,
                           error=f"{type(e).__name__}: {e}")
            if not ok:
                self._count("rejected_gate")
                return [self._note("candidate_rejected", step=step,
                                   seq=seq, reason="gate")]
        res = self.fleet.canary_swap(
            self.model, params=params, fraction=self.fraction,
            drain_timeout_s=self.drain_timeout_s)
        self.phase = "canary"
        self.canary = {"version": res["version"],
                       "from_version": res["from_version"],
                       "step": step, "seq": seq,
                       "canaries": res["canaries"], "of": res["of"],
                       "clean_ticks": 0}
        self._arm_burn_split(res["version"], res["from_version"])
        self._anom0 = self._anomaly_total()
        self._count("canaried")
        return [self._note("canary", step=step, seq=seq,
                           version=res["version"],
                           from_version=res["from_version"],
                           canaries=res["canaries"], of=res["of"])]

    def _arm_burn_split(self, new: str, old: str) -> None:
        """One SLOTracker per live build over the per-version TTFT
        histograms — the split that lets a canary burn without the
        incumbent muddying the signal."""
        self._trackers = {}
        if not self._slo_spec:
            return
        gw = self.fleet.gateway(self.model)
        for ver in (new, old):
            tr = dtrace.SLOTracker.from_spec(
                dict(self._slo_spec), clock=self._clock,
                instruments={"ttft": gw.version_ttft(ver)},
                labels={"model": self.model, "version": ver})
            if tr is not None:
                tr.tick(force=True)    # baseline the interval window
                self._trackers[ver] = tr

    def burn(self) -> Dict[str, Optional[float]]:
        """Last-computed burn per live build (diagnose's per-version
        burn column; empty outside a canary or without SLO targets)."""
        out: Dict[str, Optional[float]] = {}
        for ver, tr in self._trackers.items():
            burns = [v.get("burn") for v in
                     tr.describe()["slos"].values()
                     if v.get("burn") is not None]
            out[ver] = max(burns) if burns else None
        return out

    def _assess(self) -> List[Dict[str, Any]]:
        can = self.canary
        burn = None
        tr = self._trackers.get(can["version"])
        if tr is not None:
            snap = tr.tick(force=True)
            burns = [v.get("burn") for v in snap.values()
                     if v.get("burn") is not None]
            burn = max(burns) if burns else None
        base = self._trackers.get(can["from_version"])
        if base is not None:
            base.tick(force=True)      # keep the incumbent split live
        anomalies = self._anomaly_total() - self._anom0
        if burn is not None and burn > self.burn_high:
            return [self._rollback("slo_burn", burn=round(burn, 3))]
        if anomalies > self.anomaly_budget:
            return [self._rollback("anomaly",
                                   anomalies=int(anomalies))]
        can["clean_ticks"] += 1
        if can["clean_ticks"] < self.hold_ticks:
            return []
        res = self.fleet.promote(self.model,
                                 drain_timeout_s=self.drain_timeout_s)
        self.phase = "idle"
        self.canary = None
        self._trackers = {}
        self._count("promoted")
        return [self._note("promote", step=can["step"],
                           seq=can["seq"], version=res["version"],
                           swapped=res["swapped"])]

    def _rollback(self, reason: str, **kw) -> Dict[str, Any]:
        can = self.canary
        self.rollbacks += 1
        self.rolling_back = True
        try:
            res = self.fleet.rollback(
                self.model, reason=reason,
                drain_timeout_s=self.drain_timeout_s)
        finally:
            self.rolling_back = False
        self.phase = "idle"
        self.canary = None
        self._trackers = {}
        self._count("rolled_back")
        rec = self._note("rollback", step=can["step"], seq=can["seq"],
                         version=can["version"],
                         to_version=res["version"], reason=reason,
                         budget_left=self.max_rollbacks
                         - self.rollbacks, **kw)
        if self.rollbacks >= self.max_rollbacks:
            # budget spent: stop DEPLOYING (the last-good build keeps
            # serving) — repeated bad candidates mean the trainer or
            # the gate is broken, and a halted flywheel is a /healthz
            # cause an operator will actually see
            self.halted = True
            self._note("halt", rollbacks=self.rollbacks,
                       budget=self.max_rollbacks)
        return rec

    # -- surfaces ------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        """GET /state block + ``diagnose flywheel``: phase, pending
        candidate, per-version burn, decision history with reasons."""
        return {"model": self.model, "directory": self.directory,
                "phase": self.phase, "halted": self.halted,
                "seen_seq": self.seen_seq,
                "fraction": self.fraction,
                "hold_ticks": self.hold_ticks,
                "burn_high": self.burn_high,
                "rollbacks": self.rollbacks,
                "max_rollbacks": self.max_rollbacks,
                "canary": dict(self.canary) if self.canary else None,
                "burn": self.burn(),
                "history": [dict(h) for h in self.history[-8:]]}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FlywheelController":
        """Run the controller on a background thread at ``poll_s``
        cadence (tests call :meth:`tick` directly instead)."""
        if self._thread is not None:
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mxtpu-flywheel-{self.model}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception:
                # deployment must never die quietly; the flight ring
                # has the event, the next tick retries
                telemetry.flight().record("flywheel", "tick_error",
                                          model=self.model)

    def close(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
