"""ServeEngine — continuous batching over the llama slot KV cache.

Scheduler design (Orca, OSDI '22; slot-structured cache in the spirit
of vLLM's paged KV, SOSP '23 — one fixed bank, no paging, because XLA
wants static shapes):

- **slot bank**: ``llama.init_slot_cache`` holds ``max_slots``
  independent cache rows; per-slot ``lengths`` confine attention to
  each request's own prefix (``slot_decode_attention``).
- **admission at step boundaries**: a finished slot is overwritten in
  place by the next queued request via a per-BUCKET prefill program
  (prompts end-padded to a power of two — exact, see
  ``llama.prefill_slot``), so prefill compilations are bounded by the
  bucket count.
- **one decode program**: every step runs ``llama.decode_slots`` over
  the full bank; per-slot position/length/rng/sampling vectors make
  request churn invisible to the compiled shape. The engine asserts
  this via :attr:`compile_count`.
- **overlapped host sync**: the classic serving-latency bug is a host
  readback inside the decode loop blocking the accelerator every token
  (mxlint MXL004 flags the pattern). Here step ``t``'s tokens are read
  back only AFTER step ``t+1`` has been dispatched, so the sync runs
  under the next step's device time (``MXTPU_SERVE_OVERLAP=0`` forces
  the naive synchronous order, e.g. for latency debugging).

Determinism contract: each slot's forward and sampling depend only on
its own cache row and rng chain, so the engine's output for a request
never depends on how requests are interleaved, admitted, or delayed
(tested across slot counts and overlap modes). Against per-request
``llama.generate`` the math is identical and the rng chain replays
exactly; tokens are bit-identical in f32 (the tier-1 acceptance gate).
In reduced precision (bf16) the two attention formulations round
differently (the slot kernel accumulates in f32; the scalar-pos path
casts probs to the compute dtype), so a near-tie token can differ —
batch-size-invariance, not cross-kernel bit-equality, is the contract
there.
"""
from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import telemetry
from ..telemetry import distributed as dtrace
from ..models import llama

__all__ = ["Request", "KVHandoff", "ServeEngine", "bucket_for",
           "resume_key", "PageAllocator", "PrefixCache",
           "ngram_drafter"]

# admission wait is measured in engine steps (arrival → slot grant)
_WAIT_STEP_BUCKETS = (0.0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 1024)


_engine_seq = itertools.count(1)     # atomic: engines build on threads


def _engine_metrics(eid: str):
    """Process-wide serve metrics (one handle set per engine; the
    registry interns children, so every engine shares the TOTALS).
    Point-in-time gauges are labelled per engine instead — two live
    engines sharing one queue-depth gauge would just overwrite each
    other. Created at engine construction — the telemetry knob is
    read then."""
    return {
        "requests": telemetry.counter(
            "serve_requests_total", "Requests submitted to ServeEngine"),
        "tokens": telemetry.counter(
            "serve_tokens_total", "Tokens emitted by ServeEngine"),
        "steps": telemetry.counter(
            "serve_steps_total", "Decode steps dispatched"),
        "queue": telemetry.gauge(
            "serve_queue_depth", "Requests queued, not yet admitted",
            engine=eid),
        "slots": telemetry.gauge(
            "serve_slot_occupancy", "Active slots in the decode bank",
            engine=eid),
        "wait": telemetry.histogram(
            "serve_admission_wait_steps",
            "Engine steps between a request's arrival and its slot",
            buckets=_WAIT_STEP_BUCKETS),
        "latency": telemetry.histogram(
            "serve_token_latency_ms",
            "Inter-token gaps per request (host emission clock)"),
        # KV occupancy: the dense bank's reserved-vs-live waste number
        # ROADMAP item 1 (paged KV) is gated on (perfscope ledger)
        "kv_reserved": telemetry.gauge(
            "serve_kv_reserved_bytes",
            "Bytes the dense KV slot bank reserves", engine=eid),
        "kv_live": telemetry.gauge(
            "serve_kv_live_bytes",
            "Bytes of the slot bank covered by live sequence "
            "prefixes", engine=eid),
        "kv_occ": telemetry.gauge(
            "serve_kv_occupancy_ratio",
            "live/reserved fraction of the KV slot bank", engine=eid),
        # paged mode: the page pool the dense gauges above argue for
        "pages_total": telemetry.gauge(
            "serve_kv_pages_total",
            "Allocatable pages in the paged KV pool (scratch page 0 "
            "excluded)", engine=eid),
        "pages_free": telemetry.gauge(
            "serve_kv_pages_free",
            "Pages not mapped by any slot or prefix-cache entry",
            engine=eid),
        "pages_shared": telemetry.gauge(
            "serve_kv_pages_shared",
            "Pages mapped by more than one owner (refcount >= 2)",
            engine=eid),
        "prefix_hits": telemetry.counter(
            "serve_prefix_cache_hits_total",
            "Admissions seated on shared prefix pages (warm prefill)"),
        "prefix_misses": telemetry.counter(
            "serve_prefix_cache_misses_total",
            "Admissions that found no usable shared prefix"),
        "cow": telemetry.counter(
            "serve_cow_forks_total",
            "Copy-on-write page forks (private copy of a shared page)"),
        # speculative decoding (ISSUE 19): draft/accept accounting —
        # the accept RATE is the whole ballgame (a rejected draft costs
        # a wasted verify position), so both ends are counted
        "spec_proposed": telemetry.counter(
            "serve_spec_proposed_total",
            "Drafted tokens proposed to the speculative verify step"),
        "spec_accepted": telemetry.counter(
            "serve_spec_accepted_total",
            "Drafted tokens accepted (bit-exact match with the "
            "target chain)"),
        "spec_len": telemetry.histogram(
            "serve_spec_accepted_len",
            "Tokens emitted per slot per speculative step (1 + "
            "accepted run length)",
            buckets=(0.0, 1, 2, 3, 4, 6, 8, 12, 16)),
    }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def bucket_for(length: int, min_bucket: int, max_len: int) -> int:
    """Prefill bucket policy: the smallest power of two >= ``length``
    (floored at ``min_bucket``, capped at ``max_len``). Compilations
    are bounded by the bucket count: log2(max_len / min_bucket) + 1
    programs cover every prompt length."""
    if length > max_len:
        raise ValueError(f"prompt length {length} > max_len {max_len}")
    b = max(1, min_bucket)
    while b < length:
        b *= 2
    return min(b, max_len)


class PageAllocator:
    """Host-side refcounted allocator over the paged KV pool (the
    scheduler half of PagedAttention): pages are handed out from a free
    stack, shared read-only via :meth:`retain` (prefix sharing), and
    returned to the stack only when their last owner releases them.
    Page 0 is the SCRATCH page — never allocated, zeroed page-table
    rows alias it, redirected writes land there. Pure host state; the
    caller (ServeEngine) serializes access under its own lock."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"need >= 2 pages (scratch + 1), got {n_pages}")
        self.n_pages = int(n_pages)
        self._ref = np.zeros(self.n_pages, np.int32)
        # LIFO free stack: recently-freed pages are re-handed first
        # (their HBM is warm); page 0 is never a member
        self._free = list(range(self.n_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    @property
    def shared_pages(self) -> int:
        """Pages with more than one owner (slot rows + cache entries)."""
        return int((self._ref >= 2).sum())

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages (refcount 1 each), or None — NEVER a
        partial grant: admission must be all-or-nothing so a request
        that cannot fully seat leaves the pool untouched."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def retain(self, pages) -> None:
        """Add an owner to already-live pages (prefix sharing)."""
        for p in pages:
            if p == 0 or self._ref[p] < 1:
                raise ValueError(f"retain of non-live page {p}")
        for p in pages:
            self._ref[p] += 1

    def release(self, pages) -> None:
        """Drop one ownership per page; a page's last release frees it."""
        for p in pages:
            if p == 0 or self._ref[p] < 1:
                raise ValueError(f"release of non-live page {p}")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(int(p))


@dataclass
class _PrefixEntry:
    tokens: Tuple[int, ...]     # the full registered prompt
    n_tokens: int               # positions the pages actually cover
    pages: Tuple[int, ...]      # cache-owned (retained) pages
    hits: int = 0
    last_used: int = 0


class PrefixCache:
    """LRU map of registered prompt prefixes → the pool pages holding
    their KV (RadixAttention's sharing, flat-keyed: a handful of system
    prompts dominate real traffic, so a bounded linear scan beats a
    radix tree at this scale). Entries OWN a refcount on their pages,
    so a prefix outlives the request that prefilled it; eviction (LRU,
    or on-demand when admission runs dry) releases that hold — pages
    still mapped by live slots survive via the slots' own refs."""

    def __init__(self, allocator: PageAllocator, max_entries: int = 32):
        self._alloc = allocator
        self.max_entries = int(max_entries)
        self._entries: Dict[Tuple[int, ...], _PrefixEntry] = {}
        self._tick = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt) -> Tuple[Optional[_PrefixEntry], int]:
        """Longest registered prefix of ``prompt``, capped at
        ``len(prompt) - 1`` — the last prompt token ALWAYS runs through
        the forward pass (its logits seed the first sample)."""
        pl = len(prompt)
        pt = tuple(int(x) for x in prompt)
        best, best_m = None, 0
        for e in self._entries.values():
            cap = min(e.n_tokens, pl - 1)
            if cap <= best_m:
                continue
            m = 0
            while m < cap and pt[m] == e.tokens[m]:
                m += 1
            if m > best_m:
                best, best_m = e, m
        return best, best_m

    def pin(self, entry: _PrefixEntry) -> None:
        """Freshen an entry's LRU position WITHOUT counting a hit —
        the admission planner pins the matched entry before it
        allocates, so pool-pressure eviction prefers every other
        entry (a failed admission retries each step and must not
        inflate the hit stats)."""
        self._tick += 1
        entry.last_used = self._tick

    def touch(self, entry: _PrefixEntry) -> None:
        self.pin(entry)
        entry.hits += 1

    def insert(self, tokens, n_tokens: int, pages) -> _PrefixEntry:
        """Register ``pages`` as covering ``tokens[:n_tokens]``. The
        pages must already be live; the cache retains its own hold on
        them. Over-capacity inserts evict LRU first."""
        key = tuple(int(x) for x in tokens)
        old = self._entries.pop(key, None)
        if old is not None:
            self._alloc.release(old.pages)
        while len(self._entries) >= self.max_entries:
            if not self.evict_lru():
                break
        self._alloc.retain(pages)
        self._tick += 1
        e = _PrefixEntry(key, int(n_tokens),
                         tuple(int(p) for p in pages),
                         last_used=self._tick)
        self._entries[key] = e
        return e

    def evict_lru(self, skip: Optional[_PrefixEntry] = None) -> bool:
        """Drop the least-recently-used entry, releasing its page hold.
        ``skip`` exempts one pinned entry (the admission planner's
        matched prefix — evicting it mid-plan would free the very
        pages the plan is about to share). Returns False when nothing
        is evictable."""
        key, oldest = None, None
        for k, e in self._entries.items():
            if e is skip:
                continue
            if oldest is None or e.last_used < oldest:
                key, oldest = k, e.last_used
        if key is None:
            return False
        e = self._entries.pop(key)
        self._alloc.release(e.pages)
        return True

    def top(self, n: int = 5) -> List[Dict[str, Any]]:
        """The most-hit prefixes — diagnose/Grafana fodder."""
        es = sorted(self._entries.values(), key=lambda e: -e.hits)[:n]
        return [{"n_tokens": e.n_tokens, "hits": e.hits,
                 "pages": len(e.pages),
                 "head": list(e.tokens[:8])} for e in es]


@dataclass
class Request:
    """One generation request. ``temperature=0`` is greedy; ``seed``
    starts the request's OWN rng chain (the one ``generate`` would use
    as ``rng=PRNGKey(seed)``). ``arrival_step`` delays admission until
    that engine step — the hook seeded arrival streams (bench, tests)
    use. ``on_token(rid, token)`` streams tokens as they are
    produced; ``on_done(rid, reason)`` fires exactly once per request
    with reason ``"complete"``, ``"cancel"``/other explicit
    :meth:`ServeEngine.cancel` reasons, or ``"deadline"``.
    ``deadline_s`` is a RELATIVE budget on the engine's clock: a
    request still running (or still queued) that many seconds after
    ``submit`` is cancelled at the next step boundary — the gateway's
    slow-client defense (a stalled consumer must not hold a slot
    forever). ``rng``, when set, is an explicit (2,) uint32 chain
    state used INSTEAD of ``PRNGKey(seed)`` — the gateway's
    crash-recovery re-dispatch prefills ``prompt + already-streamed
    tokens`` with the chain fast-forwarded past them
    (:func:`resume_key`), so the resumed stream replays the exact
    sampling chain a fault-free run would have used. ``ctx``, when
    set, is the request's :class:`~mxtpu.telemetry.TraceContext`:
    every per-request span/instant the engine records (seat, prefill,
    finalize) carries its trace_id, so a multi-hop serving path
    stitches into one timeline."""
    prompt: Any
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    arrival_step: int = 0
    on_token: Optional[Callable[[int, int], None]] = None
    on_done: Optional[Callable[[int, str], None]] = None
    deadline_s: Optional[float] = None
    rng: Optional[Any] = None
    ctx: Optional[Any] = None


def cancel_counter(reason: str):
    """``serve_cancelled_total{reason}`` — the ONE definition of the
    cancel counter; every serving layer (engine, gateway, disagg)
    increments through here so the name/help/labels cannot fork."""
    return telemetry.counter(
        "serve_cancelled_total",
        "Requests ended before completion, by reason",
        reason=reason)


@jax.jit
def _fast_forward_chain(key, n):
    """``n`` carry-half splits in ONE compiled dispatch (``n`` is a
    traced operand, so one program covers every prefix length)."""
    return jax.lax.fori_loop(
        0, n, lambda _, k: jax.random.split(k)[0], key)  # noqa: MXL301 — this IS the chain primitive resume_key replays


def resume_key(seed: int, n_emitted: int) -> np.ndarray:
    """The rng chain state of a request seeded ``seed`` after it has
    emitted ``n_emitted`` tokens: every emission (the prefill's first
    token and each decode step) consumes exactly one
    ``jax.random.split``, keeping the carry half — so re-prefilling
    ``prompt + emitted`` with this key makes token ``n_emitted + 1``
    sample from the same subkey, on the same logits, as the fault-free
    run (the engine's deterministic re-dispatch contract)."""
    key = jax.random.PRNGKey(int(seed))  # noqa: MXL301 — chain ROOT:
    n = int(n_emitted)                   # resume_key defines the oracle
    if n > 0:
        key = _fast_forward_chain(key, np.int32(n))
    return np.asarray(key, np.uint32)


def ngram_drafter(history: np.ndarray, k: int) -> np.ndarray:
    """The default model-free drafter: propose the ``k`` tokens that
    followed the most recent earlier occurrence of the history's
    longest trailing n-gram (g = 3, 2, 1 — prompt/self-repetition
    lookup, cf. "prompt lookup decoding"). A match at position ``i``
    implies the stream repeats with period ``(n - g) - i``, so when
    fewer than ``k`` tokens literally follow the match the draft is
    extended cyclically — a plateau (period 1) drafts the full budget
    instead of a single token. Deterministic pure host arithmetic:
    drafting never touches the rng chain, the device, or any
    cross-request state, so speculative runs stay bit-identical and
    re-dispatch-safe no matter what this returns. Returns up to ``k``
    int32 tokens (possibly none — a draftless step emits one token
    exactly like the plain path)."""
    h = np.asarray(history, np.int64).reshape(-1)
    n = int(h.size)
    if k < 1 or n < 2:
        return np.empty(0, np.int32)
    for g in (3, 2, 1):
        if n <= g:
            continue
        tail = h[n - g:]
        for i in range(n - g - 1, -1, -1):
            if np.array_equal(h[i:i + g], tail):
                period = (n - g) - i
                out = h[[i + g + (j % period) for j in range(k)]]
                return out.astype(np.int32)
    return np.empty(0, np.int32)


@dataclass
class KVHandoff:
    """A prefill worker's detached output — everything a decode engine
    needs to seat the request without re-running the prompt
    (``llama.prefill_detached`` produces it, ``llama.inject_slot_kv``
    consumes it). ``k``/``v``: (L, n_kv_heads, bucket, hd) host
    arrays; ``rng``: the (2,) uint32 chain state AFTER the first-token
    split, so decode continues the exact chain ``generate`` would."""
    k: np.ndarray
    v: np.ndarray
    true_len: int
    token: int
    rng: np.ndarray


@dataclass
class _Dispatch:
    """One in-flight decode step: the device handle plus the host-side
    snapshot needed to attribute its tokens after the overlapped
    sync. A speculative step carries (S, W) token/valid matrices in
    ``sampled``/``emits`` instead of the plain (S,) tokens, plus the
    per-slot proposed-draft counts for the accept-rate accounting."""
    sampled: Any                                   # device (S,) int32
    slots: List[Tuple[int, int]]                   # (slot, rid) active
    firsts: List[Tuple[int, Any]]                  # (rid, device (1,))
    emits: Any = None                              # spec: device (S, W)
    proposed: Optional[np.ndarray] = None          # spec: (S,) host


class ServeEngine:
    """Continuous-batching scheduler over one model + one slot bank.

    Args: ``cfg``/``params`` — a llama config and parameter pytree
    (the weight-only int8 tree from ``quantize_params_int8`` rides the
    same programs). ``max_slots``/``max_len``/``min_bucket`` default
    from ``MXTPU_SERVE_MAX_SLOTS`` / the config's ``max_seq_len`` /
    ``MXTPU_SERVE_MIN_BUCKET``. ``mesh`` serves sharded (cache per
    ``llama.slot_cache_specs``, params as placed by the training
    rules)."""

    def __init__(self, cfg, params, *, max_slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 min_bucket: Optional[int] = None,
                 mesh=None, overlap: Optional[bool] = None,
                 clock: Optional[Callable[[], float]] = None,
                 paged: bool = False,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 int8_pages: Optional[bool] = None,
                 speculate_k: Optional[int] = None,
                 drafter: Optional[Callable] = None):
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        # deadlines are measured on THIS clock (monotonic seconds);
        # injectable so deadline/autoscale tests are deterministic
        self._clock = clock or time.monotonic
        self.max_slots = (max_slots if max_slots is not None
                          else _env_int("MXTPU_SERVE_MAX_SLOTS", 8))
        self.max_len = int(max_len or cfg.max_seq_len)
        self.min_bucket = (min_bucket if min_bucket is not None
                           else _env_int("MXTPU_SERVE_MIN_BUCKET", 16))
        self.overlap = (os.environ.get("MXTPU_SERVE_OVERLAP", "1")
                        != "0") if overlap is None else bool(overlap)
        # the engine's name in per-request trace events (EngineReplica
        # overwrites it with the replica name, so a request that moves
        # replicas shows WHICH bank served each segment)
        self.role = "engine"
        # model-build tag (fleet pools stamp this with the pool's
        # checkpoint version at spawn): joins the role in trace
        # events, so a timeline spanning a hot-swap shows which BUILD
        # served each segment, not just which replica
        self.build: Optional[str] = None

        # paged mode (PagedAttention): KV lives in a fixed page pool
        # with host-owned per-slot page tables; admission is bounded by
        # free PAGES, not slots, and prefix pages are shared CoW
        self.paged = bool(paged)
        if self.paged:
            self.page_size = int(page_size
                                 or _env_int("MXTPU_KV_PAGE_SIZE", 16))
            self._pages_per_slot = -(-self.max_len // self.page_size)
            # default pool = dense-equivalent capacity + scratch: the
            # A/B bench shrinks it to show paged admits more slots at
            # the same HBM
            self.n_pages = int(n_pages or _env_int(
                "MXTPU_KV_PAGES",
                self.max_slots * self._pages_per_slot + 1))
            self.prefix_cache_enabled = (
                prefix_cache if prefix_cache is not None
                else os.environ.get("MXTPU_KV_PREFIX_CACHE", "1")
                != "0")
            self.int8_pages = (
                bool(int8_pages) if int8_pages is not None
                else os.environ.get("MXTPU_KV_INT8_PAGES", "0") == "1")
        else:
            self.page_size = None
            self.n_pages = 0
            self.prefix_cache_enabled = False
            self.int8_pages = False

        # speculative decoding (ISSUE 19): draft k tokens host-side
        # per slot per step, verify them in ONE batched forward, and
        # advance each slot by its accepted run length. Paged-only:
        # the verify program scatters through the page-table
        # indirection (decode_slots_spec).
        self.speculate_k = int(
            speculate_k if speculate_k is not None
            else _env_int("MXTPU_SERVE_SPECULATE_K", 0))
        if self.speculate_k < 0:
            raise ValueError(
                f"speculate_k must be >= 0, got {self.speculate_k}")
        if self.speculate_k and not self.paged:
            raise ValueError(
                "speculate_k requires paged=True (the verify program "
                "runs against the paged KV layout)")
        self._drafter = drafter or ngram_drafter
        if self.speculate_k:
            # the host drafter conditions on every token emitted so
            # far, so the previous step's tokens must be read back
            # BEFORE the next step is drafted — speculative mode is
            # inherently synchronous, and its sync cost is amortized
            # over the whole accepted run rather than one token
            self.overlap = False

        if self.paged:
            state = llama.init_paged_cache(
                cfg, self.max_slots, self.n_pages, self.page_size,
                mesh=mesh, int8=self.int8_pages)
            pool_keys = (("k", "v", "ks", "vs") if self.int8_pages
                         else ("k", "v"))
            self._kv = {n: state[n] for n in pool_keys}
        else:
            state = llama.init_slot_cache(cfg, self.max_slots,
                                          self.max_len, mesh=mesh)
            self._kv = {"k": state["k"], "v": state["v"]}
        self._sv = {n: state[n] for n in ("lengths", "tokens", "rngs")}
        # the kv bank is donated through every program (in-place in
        # HBM); the small vectors are not, so the previous step's
        # sampled tokens stay readable during the overlapped sync.
        # watch(): ONE decode program ever — cache growth past 1 is the
        # spurious-recompile anomaly (recompile_total + offending key)
        telemetry.install_compile_listener()
        self._decode = telemetry.watch(
            jax.jit(partial(llama.decode_slots_paged if self.paged
                            else llama.decode_slots, cfg, mesh=mesh),
                    donate_argnums=(1,)),
            "serve_decode", expected=1, loop="serve")
        self._prefills: Dict[int, Any] = {}
        self._injects: Dict[int, Any] = {}
        self._spec_decode = None
        if self.speculate_k:
            # the ONE extra watched program speculative mode adds (the
            # k-verify step) — compile_count's bound grows by exactly
            # this; steps where no slot has a draft still run the
            # plain decode program (mixed stepping, same bank)
            self._spec_decode = telemetry.watch(
                jax.jit(partial(llama.decode_slots_spec, cfg,
                                mesh=mesh), donate_argnums=(1,)),
                "serve_spec_verify", expected=1, loop="serve")
        if self.paged:
            # host page-table (a small int32 operand per step), the
            # refcounted allocator, the prefix cache, and the CoW
            # fork program (ONE program: src/dst are traced scalars)
            self._pt = np.zeros(
                (self.max_slots, self._pages_per_slot), np.int32)
            self._pages = PageAllocator(self.n_pages)
            self._prefix = (PrefixCache(self._pages)
                            if self.prefix_cache_enabled else None)
            # a per-engine wrapper (NOT bare llama.copy_page): jit
            # caches key on callable identity, so a shared function
            # would alias cache sizes across engines and skew both the
            # recompile watcher and compile_count's churn gate
            self._copy_fn = telemetry.watch(
                jax.jit(lambda kv, src, dst: llama.copy_page(
                    kv, src, dst), donate_argnums=(0,)),
                "serve_copy_page", expected=1)
            # engine-local tallies (the telemetry counters are
            # process-wide totals shared across engines)
            self._prefix_hits = 0
            self._prefix_misses = 0
            self._cow_forks = 0
        eid = str(next(_engine_seq))
        self.engine_id = eid
        self._m = _engine_metrics(eid)
        self._m_cancel: Dict[str, Any] = {}    # per-reason counters
        # span factories pre-bind their registry histograms — the
        # per-step/per-admission hot paths must not re-intern handles
        self._span_decode = telemetry.span_factory(
            "serve.decode_step", "serve_decode_dispatch")
        self._span_prefill = telemetry.span_factory(
            "serve.prefill", "serve_prefill")
        # private resettable latency stats (always-on Histogram
        # instance, independent of the global telemetry knob)
        self._lat = telemetry.Histogram(telemetry.LATENCY_MS_BUCKETS)
        self._last_tok: Dict[int, float] = {}

        S = self.max_slots
        self._active = np.zeros(S, bool)
        self._temps = np.zeros(S, np.float32)
        self._topks = np.full(S, cfg.vocab_size, np.int32)
        self._topps = np.ones(S, np.float32)
        self._slot_rid: List[Optional[int]] = [None] * S
        # speculative mode: per-slot token history (prompt + every
        # emitted token) the host drafter conditions on, plus the
        # engine-local draft/accept tallies (all written under _lock)
        self._hist: List[List[int]] = [[] for _ in range(S)]
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_steps = 0

        # KV occupancy accounting: host-mirrored per-slot lengths (a
        # prefill seats the prompt length; every decode dispatch adds
        # one entry per active slot — exactly the device's `lengths`
        # vector, tracked WITHOUT reading it back: a device sync here
        # would block the decode loop every token, MXL004). Reserved
        # bytes count the bank's global logical size across the mesh.
        self._slot_len = np.zeros(S, np.int64)
        if self.paged:
            # per-token bytes include the scale planes in int8 mode;
            # reserved counts the whole pool (scratch page included —
            # it is real HBM)
            self._kv_reserved = int(sum(a.nbytes
                                        for a in self._kv.values()))
            self._kv_tok_bytes = (self._kv_reserved
                                  // (self.n_pages * self.page_size))
            self._m["pages_total"].set(self.n_pages - 1)
            self._m["pages_free"].set(self._pages.free_pages)
            self._m["pages_shared"].set(0)
        else:
            itemsize = np.dtype(state["k"].dtype).itemsize
            self._kv_tok_bytes = (2 * cfg.n_layers * cfg.n_kv_heads
                                  * cfg.head_dim * itemsize)
            self._kv_reserved = int(state["k"].nbytes
                                    + state["v"].nbytes)
        self._m["kv_reserved"].set(self._kv_reserved)
        self._m["kv_live"].set(0)
        self._m["kv_occ"].set(0.0)
        from ..telemetry import perfscope
        perfscope.ledger().account_tree("params", params,
                                        name=f"engine{eid}")
        perfscope.ledger().account(
            "kv_page_pool" if self.paged else "kv_slot_bank",
            self._kv_reserved, name=f"engine{eid}")

        # batch mode (run()) returns the per-request token lists, so
        # it must retain them; a long-lived gateway replica must NOT —
        # EngineReplica flips this off so request bookkeeping is
        # pruned at finalize instead of growing for the process life
        self.retain_results = True
        self._queue: List[Tuple[int, int, Request]] = []   # heap
        self._requests: Dict[int, Request] = {}
        self._results: Dict[int, List[int]] = {}
        self._done: Dict[int, bool] = {}
        self._handoffs: Dict[int, KVHandoff] = {}
        self._cancelled: Dict[int, str] = {}   # rid -> pending reason
        self._deadlines: Dict[int, float] = {}  # rid -> absolute clock
        self._ended: Dict[int, str] = {}       # rid -> final reason
        self._next_rid = 0
        self._step_idx = 0
        self.steps_run = 0
        # submit()/cancel() may run on gateway threads while the
        # engine loop steps; the lock guards the request-table state,
        # the condition wakes an idle run_forever on new work
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)

    # -- submission ----------------------------------------------------------
    def submit(self, request: Request) -> int:
        """Queue a request; returns its id. Validation mirrors
        ``generate``'s. Thread-safe (gateway threads submit while the
        engine loop runs)."""
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{request.max_new_tokens}")
        if prompt.size + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_len "
                f"{self.max_len}")
        if request.top_k is not None and request.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {request.top_k}")
        if request.top_p is not None and not 0.0 < request.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {request.top_p}")
        return self._enqueue(request)

    def submit_prefilled(self, handoff: KVHandoff,
                         request: Request) -> int:
        """Queue a request whose prompt was already prefilled on a
        prefill worker (disaggregated mode): admission seats the
        handed-off KV block via ``llama.inject_slot_kv`` instead of
        running a prefill program, and the worker-sampled first token
        is emitted as this request's first token."""
        if handoff.true_len < 1:
            raise ValueError("empty handoff")
        if handoff.true_len + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({handoff.true_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_len "
                f"{self.max_len}")
        if handoff.k.shape[2] > self.max_len:
            raise ValueError(
                f"handoff bucket {handoff.k.shape[2]} exceeds max_len "
                f"{self.max_len}")
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        if prompt.size > handoff.true_len:
            # journaled-page resume (paged mode): prompt = original +
            # already-emitted tokens; admission injects the journaled
            # pages and warm-prefills ONLY the emitted suffix — no
            # prefill-worker round trip, same rng chain (resume_key)
            if not self.paged:
                raise ValueError(
                    "handoff shorter than prompt: page-journaled "
                    "resume requires a paged engine")
            if prompt.size + request.max_new_tokens > self.max_len:
                raise ValueError(
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({request.max_new_tokens}) exceeds max_len "
                    f"{self.max_len}")
        return self._enqueue(request, handoff=handoff)

    def _enqueue(self, request: Request,
                 handoff: Optional[KVHandoff] = None) -> int:
        with self._cv:
            rid = self._next_rid
            self._next_rid += 1
            self._requests[rid] = request
            self._results[rid] = []
            self._done[rid] = False
            if handoff is not None:
                self._handoffs[rid] = handoff
            if request.deadline_s is not None:
                self._deadlines[rid] = (self._clock()
                                        + float(request.deadline_s))
            heapq.heappush(self._queue,
                           (int(request.arrival_step), rid, request))
            self._m["requests"].inc()
            self._m["queue"].set(len(self._queue))
            self._cv.notify_all()
        return rid

    # -- cancellation / deadlines --------------------------------------------
    def cancel(self, rid: int, reason: str = "cancel") -> bool:
        """Request cancellation: the rid's slot is freed at the NEXT
        step boundary (a queued rid is finalized without ever taking a
        slot) and ``serve_cancelled_total{reason}`` increments. Returns
        False if the rid is unknown or already finished."""
        with self._cv:
            if rid not in self._requests or rid in self._ended \
                    or self._done.get(rid):
                return False
            self._cancelled.setdefault(rid, reason)
            self._cv.notify_all()
        return True

    def _cancel_counter(self, reason: str):
        m = self._m_cancel.get(reason)
        if m is None:
            m = self._m_cancel[reason] = cancel_counter(reason)
        return m

    def _finalize(self, rid: int, reason: str) -> None:
        """Exactly-once request teardown (lock held): final reason,
        cancel accounting, the on_done callback, and — with
        ``retain_results`` off — pruning, so a forever-serving replica
        stays O(live requests), not O(all requests ever)."""
        if rid in self._ended:
            return
        self._ended[rid] = reason
        self._done[rid] = True
        self._deadlines.pop(rid, None)
        self._handoffs.pop(rid, None)
        self._last_tok.pop(rid, None)
        # always pruned: a stale entry here would also permanently
        # defeat _sweep_cancelled's empty-dict fast path
        self._cancelled.pop(rid, None)
        if reason != "complete":
            self._cancel_counter(reason).inc()
            telemetry.flight().record("serve", "cancelled", rid=rid,
                                      reason=reason)
        req = self._requests[rid]
        if req.ctx is not None:
            with dtrace.use(req.ctx):
                telemetry.instant("serve.done", reason=reason,
                                  role=self.role, build=self.build)
        if req.on_done is not None:
            req.on_done(rid, reason)
        if not self.retain_results:
            self._requests.pop(rid, None)
            self._results.pop(rid, None)
            self._done.pop(rid, None)
            if rid in self._slot_rid:
                # seated: its heap entry was consumed at admission, so
                # nothing else will reap the tombstone
                self._ended.pop(rid, None)
            # a queued rid's tombstone stays until _admit pops its
            # heap entry (it must not be re-admitted)

    def _sweep_cancelled(self) -> None:
        """Lock held, once per loop: expire deadlines, and finalize
        cancelled rids that hold NO slot (queued ones — active ones
        free their slot in ``_process``, the step boundary)."""
        if self._deadlines:
            now = self._clock()
            for rid, dl in list(self._deadlines.items()):
                if now >= dl and rid not in self._ended:
                    self._cancelled.setdefault(rid, "deadline")
        if not self._cancelled:
            return
        seated = set(r for r in self._slot_rid if r is not None)
        for rid, reason in list(self._cancelled.items()):
            if rid not in seated:
                self._finalize(rid, reason)

    # -- admission -----------------------------------------------------------
    # Two phases: PICK under the engine lock (queue pops + slot
    # seating + gauges — everything submit()/cancel()/load() observe),
    # then the prefill/inject PROGRAMS outside it — a first-use bucket
    # compile takes seconds on real configs, and holding the lock
    # through it would stall every submitter and the gateway's
    # routing/scrape paths behind one admission.
    def _pick_admissions(self) -> List[Tuple[int, int, Request,
                                             Optional[KVHandoff],
                                             Optional[Dict]]]:
        picks: List[Tuple[int, int, Request,
                          Optional[KVHandoff], Optional[Dict]]] = []
        while self._queue:
            arrival, rid, req = self._queue[0]
            if rid in self._ended:         # cancelled while queued
                heapq.heappop(self._queue)
                if not self.retain_results:
                    self._ended.pop(rid, None)   # tombstone reaped
                continue
            if arrival > self._step_idx:
                break
            free = np.flatnonzero(~self._active)
            if free.size == 0:
                break
            plan = None
            if self.paged:
                # paged admission is bounded by free PAGES: plan the
                # slot's table row (shared prefix + CoW fork + fresh
                # pages) before committing; a pool too full to seat
                # the head request leaves it QUEUED (backpressure,
                # never a crash) — completions free pages and retry
                plan = self._plan_pages(req, self._handoffs.get(rid))
                if plan is None:
                    break
            heapq.heappop(self._queue)
            slot = int(free[0])
            self._m["wait"].observe(max(0, self._step_idx - arrival))
            self._seat(slot, rid, req)
            if plan is not None:
                self._pt[slot, :] = 0
                row = plan["row"]
                self._pt[slot, :len(row)] = row
            if req.ctx is not None:
                # once per admission, not per token: the timeline's
                # "which bank, which slot, when" anchor for this hop
                with dtrace.use(req.ctx):
                    telemetry.instant("serve.seat", slot=slot,
                                      role=self.role)
            picks.append((slot, rid, req,
                          self._handoffs.pop(rid, None), plan))
        self._m["queue"].set(len(self._queue))
        self._m["slots"].set(int(self._active.sum()))
        if self.paged:
            self._m["pages_free"].set(self._pages.free_pages)
            self._m["pages_shared"].set(self._pages.shared_pages)
        return picks

    # -- paged admission planning (lock held) --------------------------------
    def _alloc_with_evict(self, n: int,
                          keep: Optional[_PrefixEntry] = None
                          ) -> Optional[List[int]]:
        """All-or-nothing page grant; when the pool runs dry, evict
        prefix-cache entries LRU-first (their pages come back the
        moment no live slot shares them) and retry. ``keep`` is the
        plan's matched prefix entry — never evicted by its own
        admission."""
        while True:
            pages = self._pages.alloc(n)
            if pages is not None:
                return pages
            if self._prefix is None \
                    or not self._prefix.evict_lru(skip=keep):
                return None

    def _plan_pages(self, req: Request,
                    handoff: Optional[KVHandoff]) -> Optional[Dict]:
        """Plan one paged admission: how many pages, which are shared
        from the prefix cache, where the CoW fork goes, and what gets
        registered after prefill. Returns None on page exhaustion
        (request stays queued). Mutates ONLY the allocator/prefix
        cache (under the engine lock); the device work happens later
        in ``_run_admissions``."""
        ps = self.page_size
        cap = self._pages_per_slot * ps
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        total = int(prompt.size) + int(req.max_new_tokens)
        n_total = -(-total // ps)
        entry, m = None, 0
        ignore_handoff = False
        if handoff is not None:
            # the inject block spans ceil(bucket/ps) pages — pad KV
            # beyond true_len lands in slot-owned pages (length-masked)
            n_total = max(n_total,
                          self._inject_block_len(handoff) // ps)
            tl = int(handoff.true_len)
            if (prompt.size > tl
                    and tl + bucket_for(int(prompt.size) - tl,
                                        self.min_bucket,
                                        self.max_len) > cap):
                # resume suffix bucket won't fit behind the handoff —
                # fall back to a full cold prefill with the resume rng
                # (same tokens: the chain is position-, not path-,
                # dependent)
                ignore_handoff = True
                n_total = -(-total // ps)
        elif self._prefix is not None:
            entry, m = self._prefix.lookup(prompt)
            suffix_bucket = bucket_for(int(prompt.size) - m,
                                       self.min_bucket, self.max_len)
            if entry is None or m < ps or m + suffix_bucket > cap:
                # no usable share: sub-page matches aren't worth a
                # fork, and the suffix bucket must fit the row
                entry, m = None, 0
        n_shared = m // ps
        # registration: cold admissions (and warm ones the cache can't
        # already serve maximally) register the FULL prompt; a partial
        # boundary page is copied into a cache-owned page post-prefill
        # so decode writes at >= len(prompt) never touch the entry
        register = (handoff is None and self._prefix is not None
                    and int(prompt.size) >= ps
                    and m < int(prompt.size) - 1)
        reg_partial = register and (int(prompt.size) % ps != 0)
        n_fresh = n_total - n_shared
        # Pin the matched entry and retain its pages BEFORE any
        # eviction can run: under pool pressure _alloc_with_evict
        # evicts prefix entries, and without a planner hold it could
        # free (or re-hand as "fresh") the very pages this plan is
        # about to share — retain() on a dead page would kill the
        # loop, a re-handed one would alias two logical positions.
        # The holds on the full shared pages transfer to the slot's
        # row; the boundary-page hold pins the CoW fork source until
        # the copy dispatches (_prefill_into_paged releases it).
        hold: List[int] = []
        if entry is not None:
            hold = [int(p) for p in entry.pages[:n_shared]]
            if m % ps:
                hold.append(int(entry.pages[n_shared]))
            self._pages.retain(hold)
            self._prefix.pin(entry)
        got = self._alloc_with_evict(n_fresh + (1 if reg_partial
                                                else 0), keep=entry)
        if got is None and entry is not None:
            # even with every OTHER entry evicted the warm plan does
            # not fit — drop the share and retry COLD, where the
            # matched entry itself becomes evictable (a pinned entry
            # must never wedge admission for good)
            self._pages.release(hold)
            hold, entry, m, n_shared = [], None, 0, 0
            register = (handoff is None and self._prefix is not None
                        and int(prompt.size) >= ps
                        and 0 < int(prompt.size) - 1)
            reg_partial = register and (int(prompt.size) % ps != 0)
            n_fresh = n_total
            got = self._alloc_with_evict(n_fresh + (1 if reg_partial
                                                    else 0))
        if got is None:
            if hold:
                self._pages.release(hold)   # plan abandoned: unpin
            return None
        fresh, reg_page = ((got[:-1], got[-1]) if reg_partial
                           else (got, None))
        row = np.zeros(n_total, np.int32)
        fork = None
        if entry is not None:
            row[:n_shared] = entry.pages[:n_shared]
            if m % ps:
                # the boundary page is shared but the suffix writes
                # into it — fork it into the first fresh page (the
                # planner's hold keeps the source live even if the
                # entry is evicted before the copy runs)
                fork = (int(entry.pages[n_shared]), int(fresh[0]))
            self._prefix.touch(entry)
            self._prefix_hits += 1
            self._m["prefix_hits"].inc()
        elif handoff is None and self._prefix is not None:
            self._prefix_misses += 1
            self._m["prefix_misses"].inc()
        row[n_shared:] = fresh
        reg = None
        if register:
            n_full = int(prompt.size) // ps
            reg_pages = list(row[:n_full])
            reg_copy = None
            if reg_partial:
                reg_copy = (int(row[n_full]), int(reg_page))
                reg_pages.append(int(reg_page))
            reg = {"tokens": tuple(int(t) for t in prompt),
                   "n_tokens": int(prompt.size),
                   "pages": reg_pages, "copy": reg_copy}
        return {"row": row, "prefix_len": m, "fork": fork,
                "register": reg, "ignore_handoff": ignore_handoff}

    def _run_admissions(self, picks, firsts: List[Tuple[int, Any]]
                        ) -> None:
        """Run the admission programs for already-seated picks (engine
        thread only — slot/cache state is loop-private)."""
        for slot, rid, req, handoff, plan in picks:
            with dtrace.use(req.ctx):
                if self.paged:
                    if handoff is not None:
                        firsts.append((rid, self._inject_into_paged(
                            slot, handoff, req, plan)))
                    else:
                        firsts.append((rid, self._prefill_into_paged(
                            slot, req, plan)))
                elif handoff is not None:
                    firsts.append(
                        (rid, self._inject_into(slot, handoff)))
                else:
                    firsts.append(
                        (rid, self._prefill_into(slot, req)))

    def _prefill_into(self, slot: int, req: Request):
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        bucket = bucket_for(prompt.size, self.min_bucket, self.max_len)
        fn = self._prefills.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.prefill_slot, self.cfg,
                                mesh=self.mesh), donate_argnums=(4,)),
                f"serve_prefill_b{bucket}", expected=1)
            self._prefills[bucket] = fn
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.size] = prompt
        # device-commit an explicit resume chain: a numpy key is a
        # DIFFERENT jit-cache entry from the PRNGKey device array the
        # normal path passes, so leaving it raw would recompile every
        # prefill bucket once per crash re-dispatch
        key = (jax.random.PRNGKey(req.seed) if req.rng is None  # noqa: MXL301 — chain position 0 is PRNGKey(seed) by definition; the rng branch is a mid-chain resume key
               else jax.numpy.asarray(np.asarray(req.rng, np.uint32)))
        with self._span_prefill(bucket=bucket, role=self.role):
            tok, self._kv, self._sv = fn(
                self.params, padded, np.int32(prompt.size),
                np.int32(slot), self._kv, self._sv,
                key,
                np.float32(req.temperature),
                np.int32(self.cfg.vocab_size if req.top_k is None
                         else req.top_k),
                np.float32(1.0 if req.top_p is None else req.top_p))
        with self._lock:      # host mirror of lengths — kv_cache_stats
            self._slot_len[slot] = prompt.size  # sums it under _lock
        return tok

    def _inject_into(self, slot: int, h: KVHandoff):
        """Admission program for a handed-off prefill (disaggregated
        mode): one compiled inject program per block bucket writes the
        KV block + per-slot vectors; the first token was already
        sampled on the prefill worker and is returned as a HOST array
        (``_process`` reads firsts uniformly)."""
        bucket = int(h.k.shape[2])
        fn = self._injects.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.inject_slot_kv, self.cfg,
                                mesh=self.mesh), donate_argnums=(6,)),
                f"serve_inject_b{bucket}", expected=1)
            self._injects[bucket] = fn
        with self._span_prefill(bucket=bucket, inject=True,
                                role=self.role):
            self._kv, self._sv = fn(
                h.k, h.v, np.int32(h.true_len), np.int32(slot),
                np.int32(h.token), np.asarray(h.rng, np.uint32),
                self._kv, self._sv)
        with self._lock:      # host mirror of lengths — kv_cache_stats
            self._slot_len[slot] = h.true_len  # sums it under _lock
        return np.asarray([h.token], np.int32)

    # -- paged admission programs --------------------------------------------
    def _paged_prefill_fn(self, bucket: int):
        fn = self._prefills.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.prefill_slot_paged, self.cfg,
                                mesh=self.mesh), donate_argnums=(6,)),
                f"serve_prefill_b{bucket}", expected=1)
            self._prefills[bucket] = fn
        return fn

    def _run_paged_prefill(self, slot: int, req: Request, suffix,
                           total_len: int, prefix_len: int):
        """One warm/cold paged prefill: the SUFFIX tokens (end-padded
        to their bucket) run at ``pos=prefix_len`` over the slot's
        gathered pages. The suffix bucket is what keys the program, so
        warm admissions hit SMALLER buckets than their full prompt
        would — the prefix-share TTFT win."""
        bucket = bucket_for(int(suffix.size), self.min_bucket,
                            self.max_len)
        fn = self._paged_prefill_fn(bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :suffix.size] = suffix
        key = (jax.random.PRNGKey(req.seed) if req.rng is None  # noqa: MXL301 — chain position 0 is PRNGKey(seed) by definition; the rng branch is a mid-chain resume key
               else jax.numpy.asarray(np.asarray(req.rng, np.uint32)))
        with self._span_prefill(bucket=bucket, role=self.role,
                                prefix_len=prefix_len):
            tok, self._kv, self._sv = fn(
                self.params, padded, np.int32(total_len),
                np.int32(prefix_len), self._pt[slot].copy(),
                np.int32(slot), self._kv, self._sv, key,
                np.float32(req.temperature),
                np.int32(self.cfg.vocab_size if req.top_k is None
                         else req.top_k),
                np.float32(1.0 if req.top_p is None else req.top_p))
        with self._lock:
            self._slot_len[slot] = total_len
        return tok

    def _prefill_into_paged(self, slot: int, req: Request, plan):
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        m = plan["prefix_len"]
        if plan["fork"] is not None:
            # CoW: the suffix writes into the shared boundary page —
            # give this slot a private copy first (the copy program
            # and the prefill order by data dependency on the pool)
            src, dst = plan["fork"]
            self._kv = self._copy_fn(self._kv, np.int32(src),
                                     np.int32(dst))
            with self._lock:
                self._cow_forks += 1
                # the copy is dispatched (ordered by data dependency
                # on the pool) — drop the planner's pin on the source
                self._pages.release([src])
            self._m["cow"].inc()
        tok = self._run_paged_prefill(slot, req, prompt[m:],
                                      int(prompt.size), m)
        reg = plan["register"]
        if reg is not None:
            if reg["copy"] is not None:
                # the entry's partial boundary page is a cache-owned
                # COPY of the slot's — decode writes past the prompt
                # must never leak into the registered prefix
                src, dst = reg["copy"]
                self._kv = self._copy_fn(self._kv, np.int32(src),
                                         np.int32(dst))
            with self._lock:
                self._prefix.insert(reg["tokens"], reg["n_tokens"],
                                    reg["pages"])
                if reg["copy"] is not None:
                    # insert() retains; drop the planner's temp hold
                    self._pages.release([reg["copy"][1]])
        return tok

    def _inject_block_len(self, h: KVHandoff) -> int:
        """The block length the paged inject program runs at. The
        page-granular wire trims handoff blocks to the page multiple
        covering ``true_len`` — an ARBITRARY multiple per prompt
        length — so injecting at the wire shape would compile up to
        max_len/page_size distinct programs. Pad back up to the
        power-of-two bucket (page-rounded) instead: inject compiles
        stay bounded by the bucket set, same as prefill."""
        blk = int(h.k.shape[2])
        b = bucket_for(blk, self.min_bucket, self.max_len)
        b = -(-b // self.page_size) * self.page_size
        return max(blk, b)

    def _inject_into_paged(self, slot: int, h: KVHandoff,
                           req: Request, plan):
        """Paged admission of a handed-off prefill; when the request's
        prompt is LONGER than the handoff (journaled-page resume after
        a crash), the emitted suffix warm-prefills over the injected
        pages — one admission, no prefill-worker round trip."""
        if plan.get("ignore_handoff"):
            return self._prefill_into_paged(slot, req, plan)
        bucket = self._inject_block_len(h)
        k, v = np.asarray(h.k), np.asarray(h.v)
        if bucket > k.shape[2]:
            # wire-trimmed block: zero-pad to the bucket (positions
            # past true_len are length-masked, so the fill is inert)
            pad = [(0, 0)] * k.ndim
            pad[2] = (0, bucket - k.shape[2])
            k, v = np.pad(k, pad), np.pad(v, pad)
        fn = self._injects.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.inject_paged_kv, self.cfg,
                                mesh=self.mesh), donate_argnums=(7,)),
                f"serve_inject_b{bucket}", expected=1)
            self._injects[bucket] = fn
        with self._span_prefill(bucket=bucket, inject=True,
                                role=self.role):
            self._kv, self._sv = fn(
                k, v, np.int32(h.true_len), self._pt[slot].copy(),
                np.int32(slot), np.int32(h.token),
                np.asarray(h.rng, np.uint32), self._kv, self._sv)
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size > h.true_len:
            return self._run_paged_prefill(
                slot, req, prompt[h.true_len:], int(prompt.size),
                int(h.true_len))
        with self._lock:
            self._slot_len[slot] = h.true_len
        return np.asarray([h.token], np.int32)

    def _seat(self, slot: int, rid: int, req: Request) -> None:
        self._active[slot] = True
        self._temps[slot] = req.temperature
        self._topks[slot] = (self.cfg.vocab_size if req.top_k is None
                             else req.top_k)
        self._topps[slot] = 1.0 if req.top_p is None else req.top_p
        self._slot_rid[slot] = rid
        if self.speculate_k:
            # drafting context: the prompt now, every emission later
            # (a journaled-resume prompt already carries the tokens
            # emitted before the crash — exactly the right context)
            self._hist[slot] = [
                int(t) for t in
                np.asarray(req.prompt, np.int32).reshape(-1)]

    # -- stepping ------------------------------------------------------------
    def _build_drafts(self) -> Optional[np.ndarray]:
        """Host drafting for one speculative step: up to
        ``speculate_k`` tokens per active slot from the pluggable
        drafter, clamped to ``max_new_tokens - emitted - 1`` so every
        accepted write stays inside the slot's granted pages (the
        admission plan covers prompt + max_new_tokens, and the last
        emitted token's KV is never written). Returns (S, k) int32
        with -1 marking no-draft, or None when NO slot drafted — the
        loop then runs the plain decode program (mixed stepping)."""
        K = self.speculate_k
        drafts = np.full((self.max_slots, K), -1, np.int32)
        any_d = False
        with self._lock:
            for s, rid in enumerate(self._slot_rid):
                if rid is None or not self._active[s]:
                    continue
                req = self._requests.get(rid)
                res = self._results.get(rid)
                if req is None or res is None \
                        or self._done.get(rid, True) \
                        or rid in self._cancelled:
                    continue
                hist = self._hist[s]
                # steady-state invariant: hist ends with the pending
                # token w0 (device length + 1 entries). A slot
                # admitted THIS step has its first token still
                # device-side — it drafts nothing this once
                if len(hist) <= int(self._slot_len[s]):
                    continue
                budget = min(K, int(req.max_new_tokens) - len(res) - 1)
                if budget < 1:
                    continue
                d = np.asarray(
                    self._drafter(np.asarray(hist, np.int32), budget),
                    np.int32).reshape(-1)[:budget]
                if d.size:
                    drafts[s, :d.size] = d
                    any_d = True
        return drafts if any_d else None

    def _dispatch(self, firsts) -> _Dispatch:
        # host DISPATCH time only — the program runs async; device time
        # belongs to the XLA trace (no sync in the decode loop, MXL004)
        drafts = self._build_drafts() if self.speculate_k else None
        emits = proposed = None
        with self._span_decode():
            if drafts is not None:
                # the k-verify step: one batched forward over each
                # slot's current token + drafts, accept-by-identity
                # down the same rng chain (decode_slots_spec)
                sampled, emits, self._kv, self._sv = self._spec_decode(
                    self.params, self._kv, self._sv, self._active,
                    self._pt, drafts, self._temps, self._topks,
                    self._topps)
                proposed = (drafts >= 0).sum(axis=1).astype(np.int64)
            elif self.paged:
                # the page table rides as a small int32 operand —
                # table edits at admission never touch device state
                # or the jit cache key
                sampled, self._kv, self._sv = self._decode(
                    self.params, self._kv, self._sv, self._active,
                    self._pt, self._temps, self._topks, self._topps)
            else:
                sampled, self._kv, self._sv = self._decode(
                    self.params, self._kv, self._sv, self._active,
                    self._temps, self._topks, self._topps)
        self._m["steps"].inc()
        with self._lock:
            self.steps_run += 1
            if drafts is not None:
                self._spec_steps += 1
            slots = [(s, rid) for s, rid in enumerate(self._slot_rid)
                     if self._active[s] and rid is not None]
            if emits is None:
                # the decode program appends one cache entry per
                # active slot; mirror that on the host (no readback —
                # MXL004). A speculative step advances by the accepted
                # run, known only after the sync — _process (always
                # synchronous in spec mode) mirrors it there
                for s, _rid in slots:
                    self._slot_len[s] += 1
        return _Dispatch(sampled, slots, firsts, emits=emits,
                         proposed=proposed)

    def _emit(self, rid: int, token: int, now: float) -> None:
        self._results[rid].append(token)
        self._m["tokens"].inc()
        last = self._last_tok.get(rid)
        if last is not None:
            gap_ms = 1e3 * (now - last)
            self._lat.observe(gap_ms)
            self._m["latency"].observe(gap_ms)
        self._last_tok[rid] = now
        req = self._requests[rid]
        if req.on_token is not None:
            req.on_token(rid, token)
        if len(self._results[rid]) >= req.max_new_tokens:
            self._finalize(rid, "complete")

    def _process(self, disp: _Dispatch) -> None:
        # the device sync happens OUTSIDE the lock — a submitter must
        # never block behind a device readback
        sampled = np.asarray(disp.sampled) if disp.slots else None
        emits = (np.asarray(disp.emits)
                 if disp.emits is not None and disp.slots else None)
        now = time.perf_counter()
        with self._lock:
            rid2slot = ({rid: s for s, rid in
                         enumerate(self._slot_rid) if rid is not None}
                        if self.speculate_k else {})
            for rid, dev in disp.firsts:
                if rid not in self._cancelled:
                    tok = int(np.asarray(dev)[0])
                    self._emit(rid, tok, now)
                    s = rid2slot.get(rid)
                    if s is not None:
                        self._hist[s].append(tok)
            if disp.slots:
                for slot, rid in disp.slots:
                    if emits is not None:
                        # speculative step: the device advanced this
                        # slot by its accepted run — mirror the length
                        # and emit the run in order (the emission loop
                        # stops at max_new_tokens/cancel; the device's
                        # over-advance on a finishing slot is inert —
                        # the slot is freed below and reseeded at its
                        # next admission)
                        n = int(emits[slot].sum())
                        self._slot_len[slot] += n
                        prop = int(disp.proposed[slot])
                        self._spec_proposed += prop
                        self._spec_accepted += n - 1
                        if prop:
                            self._m["spec_proposed"].inc(prop)
                            self._m["spec_accepted"].inc(n - 1)
                        self._m["spec_len"].observe(n)
                        for i in range(n):
                            # a pruned rid (non-retained, finalized)
                            # reads as done — never emit for it
                            if self._done.get(rid, True) \
                                    or rid in self._cancelled:
                                break
                            tok = int(sampled[slot, i])
                            self._emit(rid, tok, now)
                            self._hist[slot].append(tok)
                    elif not self._done.get(rid, True) \
                            and rid not in self._cancelled:
                        tok = int(sampled[slot])
                        self._emit(rid, tok, now)
                        if self.speculate_k:
                            self._hist[slot].append(tok)
            for slot, rid in enumerate(self._slot_rid):
                if rid is None:
                    continue
                reason = self._cancelled.get(rid)
                if reason is not None:
                    self._finalize(rid, reason)
                if self._done.get(rid, True):
                    self._active[slot] = False   # recycle at the next
                    self._slot_rid[slot] = None  # step boundary
                    if self.paged:
                        # release the slot's page hold; prefix-cache
                        # entries keep their own refs, so shared pages
                        # survive the request that seeded them
                        row = self._pt[slot]
                        held = [int(p) for p in row if p]
                        if held:
                            self._pages.release(held)
                        row[:] = 0
            self._m["slots"].set(int(self._active.sum()))
            if self.paged:
                self._m["pages_free"].set(self._pages.free_pages)
                self._m["pages_shared"].set(self._pages.shared_pages)
            live = (int(self._slot_len[self._active].sum())
                    * self._kv_tok_bytes)
            self._m["kv_live"].set(live)
            self._m["kv_occ"].set(live / self._kv_reserved
                                  if self._kv_reserved else 0.0)

    # -- the serving loop ----------------------------------------------------
    def _loop_iter(self, prev: Optional[_Dispatch]
                   ) -> Optional[_Dispatch]:
        """One engine step: sweep cancels/deadlines, admit, dispatch,
        and (overlap permitting) process the PREVIOUS step's tokens
        under this step's device time. Shared by :meth:`run` (batch
        drain) and :meth:`run_forever` (the gateway's replica loop)."""
        firsts: List[Tuple[int, Any]] = []
        with self._lock:
            self._sweep_cancelled()
            picks = self._pick_admissions()
        self._run_admissions(picks, firsts)
        # any admission leaves its slot active, so firsts are
        # always carried by a dispatch
        out = (self._dispatch(firsts) if self._active.any()
               else None)
        if not self.overlap and out is not None:
            self._process(out)
            out = None
        if prev is not None:
            self._process(prev)
        with self._lock:
            self._step_idx += 1
        return out

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue: admit → dispatch → (overlapped) process,
        until every submitted request has completed. Returns
        {rid: generated tokens} (prompts not included, matching the
        ``generate`` continuation; a cancelled request's entry holds
        whatever tokens it produced before its cancellation)."""
        prev: Optional[_Dispatch] = None
        while True:
            with self._lock:
                if not (self._queue or self._active.any()
                        or prev is not None):
                    break
            prev = self._loop_iter(prev)
            with self._lock:
                if (prev is None and not self._active.any()
                        and self._queue):
                    # idle until the next scheduled arrival
                    self._step_idx = max(self._step_idx,
                                         self._queue[0][0])
        with self._lock:
            return {rid: np.asarray(toks, np.int32)
                    for rid, toks in self._results.items()}

    def run_forever(self, stop: threading.Event,
                    idle_wait: float = 0.02) -> None:
        """The replica loop: serve submissions as they arrive until
        ``stop`` is set, then DRAIN — in-flight and queued requests
        finish (or hit their deadlines) before the loop exits, so a
        scale-down never drops accepted work. Idle waits block on the
        submit/cancel condition, bounded by ``idle_wait`` so a stop
        with no traffic is noticed promptly."""
        prev: Optional[_Dispatch] = None
        while True:
            with self._cv:
                work = (bool(self._queue) or self._active.any()
                        or prev is not None)
                if not work:
                    if stop.is_set():
                        break
                    self._cv.wait(idle_wait)
                    continue
                if (prev is None and not self._active.any()
                        and self._queue
                        and self._queue[0][0] > self._step_idx):
                    # future-only arrivals (seeded streams): jump the
                    # step clock instead of spinning
                    self._step_idx = self._queue[0][0]
            prev = self._loop_iter(prev)

    def wake(self) -> None:
        """Nudge an idle :meth:`run_forever` (the gateway calls this
        right after setting the stop event)."""
        with self._cv:
            self._cv.notify_all()

    def load(self) -> Dict[str, int]:
        """Routing snapshot: queued (submitted, not yet seated),
        active slots, and the bank size — what the gateway's
        least-loaded router and autoscaler read."""
        with self._lock:
            queued = sum(1 for _, rid, _r in self._queue
                         if rid not in self._ended)
            return {"queued": queued,
                    "active": int(self._active.sum()),
                    "slots": self.max_slots}

    # -- introspection -------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Compiled programs this engine has built: one per admission
        bucket (prefill or, in disaggregated mode, inject) + the
        single decode program. The churn test gates this at
        ``buckets + 1`` — requests entering/leaving must never
        retrace."""
        # deliberately NO fallback: if jax moves the private
        # _cache_size API this raises loudly — a silent
        # len(fns) stand-in would make the no-retrace gate
        # vacuously true exactly when a retrace bug could hide
        fns = ([self._decode] + list(self._prefills.values())
               + list(self._injects.values()))
        if self.paged:
            # the CoW fork/registration copy is ONE program (src/dst
            # are traced scalars) — the paged bound is buckets + 2
            fns.append(self._copy_fn)
        if self._spec_decode is not None:
            # speculative mode adds exactly ONE watched program (the
            # k-verify step) — the spec bound is buckets + 3
            fns.append(self._spec_decode)
        return int(sum(f._cache_size() for f in fns))

    @property
    def n_buckets(self) -> int:
        """Distinct admission buckets compiled so far — prefill
        programs plus (disaggregated mode) inject programs; the
        compile bound is ``n_buckets + 1`` either way."""
        return len(self._prefills) + len(self._injects)

    def kv_cache_stats(self) -> Dict[str, Any]:
        """KV slot-bank occupancy: bytes the dense bank RESERVES vs
        bytes live sequence prefixes actually COVER — the exact waste
        number ROADMAP item 1 (paged KV) is gated on, surfaced in the
        gateway ``/state`` block. Host arithmetic only (the mirrored
        per-slot lengths; reading the device ``lengths`` vector here
        would put a sync next to the decode loop — MXL004)."""
        with self._lock:
            active = int(self._active.sum())
            live_tokens = int(self._slot_len[self._active].sum())
            out = {"slots": self.max_slots, "active": active,
                   "reserved_bytes": self._kv_reserved}
            if self.paged:
                out.update({
                    "paged": True,
                    "page_size": self.page_size,
                    "pages_total": self.n_pages - 1,
                    "pages_free": self._pages.free_pages,
                    "pages_used": self._pages.used_pages,
                    "pages_shared": self._pages.shared_pages,
                    "cow_forks": self._cow_forks,
                    "prefix_hits": self._prefix_hits,
                    "prefix_misses": self._prefix_misses,
                    "prefix_entries": (len(self._prefix)
                                       if self._prefix is not None
                                       else 0),
                    "top_prefixes": (self._prefix.top()
                                     if self._prefix is not None
                                     else []),
                })
                if self.speculate_k:
                    prop = self._spec_proposed
                    out.update({
                        "speculate_k": self.speculate_k,
                        "spec_proposed": prop,
                        "spec_accepted": self._spec_accepted,
                        "spec_accept_rate": (
                            self._spec_accepted / prop if prop
                            else 0.0),
                        "spec_steps": self._spec_steps,
                    })
        live = live_tokens * self._kv_tok_bytes
        out["live_bytes"] = live
        out["occupancy"] = (live / self._kv_reserved
                            if self._kv_reserved else 0.0)
        return out

    def latency_stats(self) -> Dict[str, float]:
        """Per-token latency: p50/p99 over the gaps between a
        request's consecutive tokens (ms), from this engine's private
        fixed-bucket histogram (bounded memory — the unbounded
        per-token log it replaces grew with every request; the same
        gaps also feed the process-wide ``serve_token_latency_ms``)."""
        n = self._lat.count
        if n == 0:
            return {"p50_token_ms": 0.0, "p99_token_ms": 0.0,
                    "n_gaps": 0}
        return {"p50_token_ms": float(self._lat.percentile(50)),
                "p99_token_ms": float(self._lat.percentile(99)),
                "n_gaps": n}

    def reset_stats(self) -> None:
        """Zero the per-engine latency histogram + step counter (the
        bench warmup boundary). Speculative accept counters reset with
        it so a bench's accept rate excludes warmup traffic."""
        with self._lock:      # _emit observes/updates these under _lock
            self._lat.reset()
            self._last_tok.clear()
            self.steps_run = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._spec_steps = 0
