"""HTTP front door — stdlib ``http.server`` only, matching the
kvstore's no-deps style (the reference shipped its serving fronts the
same way: no framework, one file).

Endpoints:

- ``POST /v1/generate`` — body ``{"prompt": [ints],
  "max_new_tokens": n, "temperature": t, "top_k": k, "top_p": p,
  "seed": s, "deadline_s": d, "stream": true}``. Streamed responses
  are newline-delimited JSON (``{"token": t}`` per token, then one
  ``{"done": true, "reason": ..., "tokens": [...]}`` trailer — the
  trailer repeats the full list so a client that missed flushes can
  still verify). ``stream: false`` returns one JSON object.
  Overload → ``429`` with ``Retry-After``; bad request → ``400``.
- ``GET /metrics`` — the process-wide Prometheus dump
  (``telemetry.prometheus()``), gateway gauges included.
- ``GET /state`` — live replica/queue topology (tools/diagnose.py
  renders it).
- ``GET /healthz`` — liveness.

HTTP/1.0, one connection per request: the stream ends when the socket
closes, so clients need no chunked-decoding. A client that disconnects
mid-stream cancels its request (reason ``disconnect``) — the slot
frees at the next step boundary instead of decoding to a dead socket.
"""
from __future__ import annotations

import json
import queue as _queue
import socket
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from .gateway import Gateway, GatewayOverloaded, GatewayUnavailable

__all__ = ["serve_http", "GatewayClient"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.0"
    server_version = "mxtpu-gateway"

    def log_message(self, *args):      # no per-request stderr spam —
        pass                           # telemetry carries the counters

    @property
    def gw(self) -> Gateway:
        return self.server.gateway     # type: ignore[attr-defined]

    @staticmethod
    def _build_fields(handle) -> Dict[str, Any]:
        """Fleet provenance on every response: which model served it,
        and which BUILD — across a hot-swap, the version label is how
        a client (or the bench's bit-identity check) knows whether
        old or new weights produced these tokens. Absent for
        single-model deployments (responses unchanged)."""
        out: Dict[str, Any] = {}
        if getattr(handle, "model", None) is not None:
            out["model"] = handle.model
            out["version"] = handle.version
        return out

    def _json(self, code: int, obj: Dict[str, Any],
              headers: Dict[str, str] = ()) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in dict(headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            # liveness plus the degradation story: load balancers key
            # on "status" ("ok" / "degraded"), humans read the rest
            self._json(200, self.gw.health())
        elif self.path == "/metrics":
            # federated when peers are configured: the fleet view
            # with per-process labels, the plain local dump otherwise
            body = self.gw.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/state":
            self._json(200, self.gw.state())
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path != "/v1/generate":
            self._json(404, {"error": f"no route {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, TypeError) as e:
            self._json(400, {"error": f"bad json: {e}"})
            return
        try:
            # an upstream proxy's trace id joins this request to a
            # larger trace; absent, the gateway mints one — either
            # way the response carries it back for correlation
            handle = self.gw.submit_dict(
                body, trace_id=self.headers.get("X-Mxtpu-Trace"))
        except GatewayOverloaded as e:
            self._json(429, {"error": str(e),
                             "retry_after_s": e.retry_after},
                       {"Retry-After": str(e.retry_after)})
            return
        except GatewayUnavailable as e:
            # zero healthy replicas: a DIFFERENT failure from
            # overload — 503 says "the backend is down, retry later",
            # with the same jittered Retry-After discipline
            self._json(503, {"error": str(e),
                             "retry_after_s": e.retry_after},
                       {"Retry-After": str(e.retry_after)})
            return
        except (ValueError, KeyError, TypeError) as e:
            self._json(400, {"error": str(e)})
            return
        if not body.get("stream", True):
            try:
                toks = handle.result()
            except TimeoutError:
                # a request that never finishes (no deadline set, a
                # stalled pool) must not leak its slot: cancel, 504
                handle.cancel("timeout")
                self._json(504, {"error": "request timed out at the "
                                          "gateway"})
                return
            self._json(200, {"tokens": [int(t) for t in toks],
                             "reason": handle.reason,
                             "trace_id": handle.trace_id,
                             **self._build_fields(handle)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        try:
            for tok in handle.stream():
                self.wfile.write(
                    json.dumps({"token": tok}).encode() + b"\n")
                self.wfile.flush()
            self.wfile.write(json.dumps(
                {"done": True, "reason": handle.reason,
                 "tokens": handle.tokens,
                 "trace_id": handle.trace_id,
                 **self._build_fields(handle)}).encode() + b"\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the slow-client story: a dead consumer must not hold a
            # decode slot — cancel and let the step boundary reclaim
            handle.cancel("disconnect")
        except _queue.Empty:
            # no token for the whole stream timeout: reclaim the slot
            # and end the stream with an honest trailer
            handle.cancel("timeout")
            try:
                self.wfile.write(json.dumps(
                    {"done": True, "reason": "timeout",
                     "tokens": handle.tokens}).encode() + b"\n")
                self.wfile.flush()
            except OSError:
                pass


def serve_http(gateway: Gateway, host: str,
               port: int) -> Tuple[ThreadingHTTPServer, int]:
    """Bind + serve on a daemon thread; returns (server, bound_port)."""
    import threading
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    srv.gateway = gateway            # type: ignore[attr-defined]
    threading.Thread(target=srv.serve_forever, kwargs={
        "poll_interval": 0.05}, daemon=True,
        name="mxtpu-gw-http").start()
    return srv, srv.server_address[1]


class GatewayClient:
    """Minimal test/bench client (stdlib sockets — the front door is
    HTTP/1.0, so responses end at close; no chunked decoding needed).

    ``generate`` returns a record with the tokens AND client-side
    timestamps per token — what the gateway bench turns into TTFT and
    inter-token percentiles."""

    def __init__(self, host: str, port: int, timeout: float = 300.0):
        self.addr = (host, port)
        self.timeout = timeout

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None) -> Tuple[int,
                                                        Dict[str, str],
                                                        Any]:
        sock = socket.create_connection(self.addr,
                                        timeout=self.timeout)
        try:
            head = (f"{method} {path} HTTP/1.0\r\n"
                    f"Host: {self.addr[0]}\r\n")
            if body is not None:
                head += (f"Content-Length: {len(body)}\r\n"
                         "Content-Type: application/json\r\n")
            sock.sendall(head.encode() + b"\r\n" + (body or b""))
            f = sock.makefile("rb")
            status = int(f.readline().split()[1])
            headers: Dict[str, str] = {}
            while True:
                line = f.readline().strip()
                if not line:
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            return status, headers, f
        except Exception:
            sock.close()
            raise

    def get_json(self, path: str) -> Tuple[int, Any]:
        status, _, f = self._request("GET", path)
        with f:
            return status, json.loads(f.read() or b"{}")

    def get_text(self, path: str) -> Tuple[int, str]:
        status, _, f = self._request("GET", path)
        with f:
            return status, f.read().decode()

    def generate(self, prompt, max_new_tokens: int,
                 **kw) -> Dict[str, Any]:
        """One streamed request. Returns ``{"status", "tokens",
        "reason", "times"|"retry_after_s"|"error"}`` — times are
        client-receipt perf_counter stamps, one per token."""
        body = json.dumps(dict(prompt=[int(t) for t in prompt],
                               max_new_tokens=int(max_new_tokens),
                               stream=True, **kw)).encode()
        t0 = time.perf_counter()
        status, headers, f = self._request("POST", "/v1/generate",
                                           body)
        tokens: List[int] = []
        times: List[float] = []
        reason = None
        with f:
            if status != 200:
                err = json.loads(f.read() or b"{}")
                rec = {"status": status, "t0": t0, "tokens": tokens,
                       "times": times, "reason": None,
                       "error": err.get("error")}
                if "retry-after" in headers:
                    rec["retry_after_s"] = int(headers["retry-after"])
                return rec
            trace_id = None
            model = version = None
            for line in f:
                evt = json.loads(line)
                if evt.get("done"):
                    reason = evt.get("reason")
                    tokens = [int(t) for t in evt["tokens"]]
                    trace_id = evt.get("trace_id")
                    model = evt.get("model")
                    version = evt.get("version")
                    break
                times.append(time.perf_counter())
                tokens.append(int(evt["token"]))
        rec = {"status": status, "t0": t0, "tokens": tokens,
               "times": times[:len(tokens)], "reason": reason,
               "trace_id": trace_id}
        if model is not None:
            rec["model"] = model
            rec["version"] = version
        return rec
