"""Disaggregated prefill/decode (DistServe, OSDI '24): prefill is
compute-bound (one big batched matmul pass over the prompt), decode is
memory-bound (weight+KV streaming per token) — colocating them makes
each steal the other's resource. This module splits them into
independent pools joined by a KV handoff:

- :class:`PrefillWorker` — runs ``llama.prefill_detached`` (one
  compiled program per prompt bucket), reads the per-request KV block
  back to host, and ships it over the channel.
- :class:`KVChannel` — the handoff wire: ``mxtpu.rpc`` framed
  messages (same codec + HMAC + frame-size ceiling as the kvstore)
  over a socketpair (same host) or TCP (``listen``/``connect`` — the
  cross-host deployment, prefill pool on compute-heavy hosts, decode
  pool on HBM-heavy ones).
- :class:`DisaggBackend` — the Gateway-facing composition: routes
  prompts to the least-queued prefill worker, a feeder thread receives
  handoffs and seats them in the least-loaded decode replica via
  ``ServeEngine.submit_prefilled`` (→ ``llama.inject_slot_kv``).

Bit-identity: ``prefill_detached`` is the same forward graph, sampler
and rng chain as ``prefill_slot``; the block crosses the wire as raw
bytes; ``inject_slot_kv`` is the scatter ``prefill_slot`` would have
done. So a disaggregated request's tokens are bit-identical to the
colocated engine AND to per-request ``generate`` (tier-1-gated).
"""
from __future__ import annotations

import itertools
import queue
import socket
import threading
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ... import rpc, telemetry
from ...base import env_str
from ...models import llama
from ..engine import KVHandoff, Request, ServeEngine, bucket_for
from .replica import ReplicaSet, Ticket

__all__ = ["KVChannel", "PrefillWorker", "DisaggBackend"]


def _channel_secret() -> bytes:
    return env_str(
        "MXTPU_GATEWAY_SECRET", "",
        "Shared secret for the gateway KV-handoff channel: every "
        "handoff frame is HMAC-SHA256-authenticated when set (the "
        "kvstore wire discipline). REQUIRED when prefill and decode "
        "pools ride TCP across hosts.").encode()


class KVChannel:
    """One framed-RPC handoff pipe. Thread-safe on both sides (many
    prefill workers share the send side; one feeder drains the
    receive side)."""

    def __init__(self, sock: socket.socket,
                 secret: Optional[bytes] = None):
        self._sock = sock
        self._secret = (_channel_secret() if secret is None
                        else secret)
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._m_bytes = telemetry.histogram(
            "gateway_kv_handoff_bytes",
            "KV-handoff frame sizes on the prefill→decode channel",
            buckets=telemetry.BYTES_BUCKETS)
        self._m_count = telemetry.counter(
            "gateway_kv_handoffs_total",
            "KV blocks shipped prefill→decode")

    @classmethod
    def pair(cls, secret: Optional[bytes] = None
             ) -> Tuple["KVChannel", "KVChannel"]:
        """Same-process pair (the in-tree topology: pools as thread
        groups, handoff still through the real wire codec)."""
        a, b = socket.socketpair()
        return cls(a, secret=secret), cls(b, secret=secret)

    @classmethod
    def listen(cls, host: str = "127.0.0.1", port: int = 0,
               secret: Optional[bytes] = None
               ) -> Tuple[socket.socket, int]:
        """Decode-side accept socket for cross-host pools; returns
        (listener, bound_port) — call :meth:`accept` next."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(8)
        return srv, srv.getsockname()[1]

    @classmethod
    def accept(cls, listener: socket.socket,
               secret: Optional[bytes] = None) -> "KVChannel":
        conn, _ = listener.accept()
        return cls(conn, secret=secret)

    @classmethod
    def connect(cls, host: str, port: int,
                secret: Optional[bytes] = None,
                timeout: float = 30.0) -> "KVChannel":
        return cls(socket.create_connection((host, port),
                                            timeout=timeout),
                   secret=secret)

    def send(self, msg: Any) -> None:
        with self._send_lock:
            n = rpc.send_msg(self._sock, msg, self._secret)
        self._m_bytes.observe(n)
        self._m_count.inc()

    def recv(self) -> Any:
        with self._recv_lock:
            msg, _ = rpc.recv_msg(self._sock, self._secret)
        return msg

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def handoff_to_wire(rid: int, h: KVHandoff) -> tuple:
    return ("kv", int(rid), int(h.true_len), int(h.token),
            np.asarray(h.k), np.asarray(h.v),
            np.asarray(h.rng, np.uint32))


def wire_to_handoff(msg: tuple) -> Tuple[int, KVHandoff]:
    if not (isinstance(msg, tuple) and len(msg) == 7
            and msg[0] == "kv"):
        raise rpc.RPCProtocolError(
            f"not a KV-handoff frame: {str(msg)[:80]}")
    _, rid, true_len, token, k, v, rng = msg
    return int(rid), KVHandoff(k=k, v=v, true_len=int(true_len),
                               token=int(token), rng=rng)


class PrefillWorker:
    """One prefill compute thread: pops (rid, Request) jobs, runs the
    bucketed ``prefill_detached`` program, host-gathers the block (the
    sync IS this pool's job — decode never blocks on it) and ships it
    over the channel."""

    def __init__(self, cfg, params, channel: KVChannel, *,
                 min_bucket: int, max_len: int, mesh=None,
                 name: str = "p0"):
        self.cfg = cfg
        self.params = params
        self.channel = channel
        self.min_bucket = min_bucket
        self.max_len = max_len
        self.mesh = mesh
        self.name = name
        self._fns: Dict[int, Any] = {}
        self._jobs: "queue.Queue[Any]" = queue.Queue()
        self._span = telemetry.span_factory("gateway.prefill",
                                            "gateway_prefill")
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mxtpu-gw-prefill-{name}")
        self._thread.start()

    def submit(self, rid: int, req: Request) -> None:
        self._jobs.put((rid, req))

    def pending(self) -> int:
        return self._jobs.qsize()

    def stop(self, join: bool = True, timeout: float = 60.0) -> None:
        self._jobs.put(None)
        if join:
            self._thread.join(timeout)

    @property
    def compile_count(self) -> int:
        return int(sum(f._cache_size() for f in self._fns.values()))

    def _fn(self, bucket: int):
        fn = self._fns.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.prefill_detached, self.cfg,
                                mesh=self.mesh)),
                f"gateway_prefill_b{bucket}", expected=1)
            self._fns[bucket] = fn
        return fn

    def _loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            rid, req = job
            try:
                prompt = np.asarray(req.prompt, np.int32).reshape(-1)
                bucket = bucket_for(prompt.size, self.min_bucket,
                                    self.max_len)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :prompt.size] = prompt
                V = self.cfg.vocab_size
                with self._span(bucket=bucket):
                    tok, kb, vb, rng = self._fn(bucket)(
                        self.params, padded, np.int32(prompt.size),
                        jax.random.PRNGKey(req.seed),
                        np.float32(req.temperature),
                        np.int32(V if req.top_k is None
                                 else req.top_k),
                        np.float32(1.0 if req.top_p is None
                                   else req.top_p))
                h = KVHandoff(k=np.asarray(kb), v=np.asarray(vb),
                              true_len=int(prompt.size),
                              token=int(np.asarray(tok)[0]),
                              rng=np.asarray(rng, np.uint32))
                self.channel.send(handoff_to_wire(rid, h))
            except (ConnectionError, OSError):
                return          # channel gone: pool is shutting down
            except Exception as e:
                # a failed prefill (device error, bad state) must not
                # kill the worker and strand every later request: the
                # error frame lets the feeder finalize THIS rid and
                # the loop keeps serving
                telemetry.counter(
                    "gateway_prefill_errors_total",
                    "Prefill jobs that failed on a worker").inc()
                telemetry.flight().record("gateway", "prefill_error",
                                          rid=rid, worker=self.name,
                                          error=repr(e)[:200])
                try:
                    self.channel.send(("kverr", int(rid),
                                       repr(e)[:200]))
                except (ConnectionError, OSError):
                    return


class DisaggBackend:
    """Prefill pool + decode replicas + the feeder joining them — the
    same routing surface ``ReplicaSet`` gives the Gateway. The
    autoscaler's ``scale_to`` moves the DECODE pool (the memory-bound
    side, where slots live); the prefill pool is sized at
    construction."""

    def __init__(self, cfg, params, *, n_prefill: int = 1,
                 n_decode: int = 1, max_slots: int = 4,
                 max_len: Optional[int] = None,
                 min_bucket: Optional[int] = None, mesh=None,
                 channel: Optional[Tuple[KVChannel, KVChannel]] = None,
                 clock=None, started: bool = True):
        max_len = int(max_len or cfg.max_seq_len)
        min_bucket = int(min_bucket or 16)
        tx, rx = channel if channel is not None else KVChannel.pair()
        self._tx, self._rx = tx, rx
        self.decode = ReplicaSet(
            lambda: ServeEngine(cfg, params, max_slots=max_slots,
                                max_len=max_len, min_bucket=min_bucket,
                                mesh=mesh, clock=clock),
            n_decode, started=started)
        self.prefill: List[PrefillWorker] = [
            PrefillWorker(cfg, params, tx, min_bucket=min_bucket,
                          max_len=max_len, mesh=mesh, name=f"p{i}")
            for i in range(max(1, n_prefill))]
        import time as _time
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._seq = itertools.count()
        # rid -> (request, ticket, submit time on self._clock)
        self._pending: Dict[int, Tuple[Request, "_DisaggTicket",
                                       float]] = {}
        self._feeder = threading.Thread(target=self._feed, daemon=True,
                                        name="mxtpu-gw-kv-feeder")
        self._feeder.start()

    # -- Gateway surface -----------------------------------------------------
    def route(self, req: Request, handoff=None) -> "Ticket":
        if handoff is not None:
            return self.decode.route(req, handoff=handoff)
        # validate NOW (the prefill thread can only log, not raise to
        # the caller) — same checks ServeEngine.submit applies
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{req.max_new_tokens}")
        if prompt.size + req.max_new_tokens > self._max_len():
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_len")
        if req.top_k is not None and req.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {req.top_k}")
        if req.top_p is not None and not 0.0 < req.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got "
                             f"{req.top_p}")
        ticket = _DisaggTicket(self)
        with self._lock:
            rid = next(self._seq)
            ticket.rid = rid
            self._pending[rid] = (req, ticket, self._clock())
        worker = min(self.prefill, key=lambda w: w.pending())
        worker.submit(rid, req)
        return ticket

    def load_total(self) -> Dict[str, int]:
        out = self.decode.load_total()
        with self._lock:
            out["queued"] += len(self._pending)
        return out

    def state(self) -> List[Dict[str, Any]]:
        with self._lock:
            n_pending = len(self._pending)
        return ([dict(name=w.name, role="prefill", alive=True,
                      queued=w.pending(), active=0, slots=0)
                 for w in self.prefill]
                + [dict(r, role="decode")
                   for r in self.decode.state()]
                + [dict(name="handoff", role="channel", alive=True,
                        queued=n_pending, active=0, slots=0)])

    @property
    def size(self) -> int:
        return self.decode.size

    def scale_to(self, n: int) -> int:
        return self.decode.scale_to(n)

    def start(self) -> None:
        self.decode.start()

    def close(self) -> None:
        for w in self.prefill:
            w.stop(join=True)
        self._tx.close()
        self._rx.close()
        self._feeder.join(10.0)
        self.decode.close()

    # -- internals -----------------------------------------------------------
    def _max_len(self) -> int:
        return self.prefill[0].max_len

    @staticmethod
    def _count_cancel(reason: str) -> None:
        telemetry.counter(
            "serve_cancelled_total",
            "Requests ended before completion, by reason",
            reason=reason).inc()

    def _feed(self) -> None:
        while True:
            try:
                msg = self._rx.recv()
            except (ConnectionError, OSError):
                return                      # channel closed: shutdown
            if (isinstance(msg, tuple) and len(msg) == 3
                    and msg[0] == "kverr"):
                rid, err = int(msg[1]), msg[2]
                with self._lock:
                    entry = self._pending.pop(rid, None)
                if entry is not None and entry[0].on_done is not None:
                    entry[0].on_done(rid, "error")
                if entry is not None:
                    self._count_cancel("error")
                continue
            try:
                rid, handoff = wire_to_handoff(msg)
            except rpc.RPCProtocolError as e:
                # a foreign frame means the stream is desynced — stop
                # feeding loudly rather than seat corrupt state
                telemetry.flight().record("gateway", "kv_channel_error",
                                          error=repr(e)[:200])
                return
            with self._lock:
                entry = self._pending.pop(rid, None)
                reason = (entry[1].cancelled_reason
                          if entry is not None else None)
            if entry is None:
                continue                    # cancelled while prefilling
            req, ticket, t_submit = entry
            if reason is None and req.deadline_s is not None:
                # the budget started at SUBMIT, not at seating: a
                # request that burned it queued behind prefill expires
                # here, and a survivor decodes on the REMAINDER
                elapsed = self._clock() - t_submit
                if elapsed >= req.deadline_s:
                    reason = "deadline"
                else:
                    req.deadline_s = req.deadline_s - elapsed
            if reason is not None:
                self._count_cancel(reason)
                if req.on_done is not None:
                    req.on_done(rid, reason)
                continue
            seated = self.decode.route(req, handoff=handoff)
            with self._lock:
                ticket.seated = seated
                reason = ticket.cancelled_reason
            if reason is not None:          # cancel raced the seating
                seated.cancel(reason)


class _DisaggTicket:
    """Cancellation handle across the two phases: before the handoff
    lands the request only exists in ``_pending`` (cancel = drop +
    fire on_done); after seating it is a decode-engine rid."""

    def __init__(self, backend: DisaggBackend):
        self._backend = backend
        self.rid: Optional[int] = None
        self.seated: Optional[Ticket] = None
        self.cancelled_reason: Optional[str] = None

    def cancel(self, reason: str = "cancel") -> bool:
        with self._backend._lock:
            if self.seated is not None:
                seated = self.seated
            else:
                # pending (or mid-handoff): the feeder checks the
                # reason under this same lock before/after seating
                self.cancelled_reason = reason
                entry = self._backend._pending.pop(self.rid, None)
                seated = None
        if seated is not None:
            return seated.cancel(reason)
        if entry is None:
            return True          # feeder will honor cancelled_reason
        req = entry[0]
        self._backend._count_cancel(reason)
        if req.on_done is not None:
            req.on_done(self.rid, reason)
        return True
