"""Disaggregated prefill/decode (DistServe, OSDI '24): prefill is
compute-bound (one big batched matmul pass over the prompt), decode is
memory-bound (weight+KV streaming per token) — colocating them makes
each steal the other's resource. This module splits them into
independent pools joined by a KV handoff:

- :class:`PrefillWorker` — runs ``llama.prefill_detached`` (one
  compiled program per prompt bucket), reads the per-request KV block
  back to host, and ships it over the channel.
- :class:`KVChannel` — the handoff wire: ``mxtpu.rpc`` framed
  messages (same codec + HMAC + frame-size ceiling as the kvstore)
  over a socketpair (same host) or TCP (``listen``/``connect`` — the
  cross-host deployment, prefill pool on compute-heavy hosts, decode
  pool on HBM-heavy ones).
- :class:`DisaggBackend` — the Gateway-facing composition: routes
  prompts to the least-queued prefill worker, a feeder thread receives
  handoffs and seats them in the least-loaded decode replica via
  ``ServeEngine.submit_prefilled`` (→ ``llama.inject_slot_kv``).

Self-healing (PR 7): TCP channels carry an HMAC hello handshake on
every (re)connect and an ACK per handoff frame. A severed connection
reconnects with exponential backoff (``rpc.connect_with_backoff`` —
the kvstore client discipline, shared) and RESENDS the un-acked frame;
the receive side re-accepts and the pending-table pop dedups a frame
whose ack (not delivery) was lost. A wrong secret fails the handshake
FAST (``RPCAuthError`` — never retried); a corrupted frame from an
already-authenticated peer poisons only that connection (drop +
re-accept + resend). A prefill worker that dies is respawned and its
in-flight job resubmitted ONCE (the DataLoader dead-worker pattern);
sustained prefill-path failure trips a circuit breaker that falls
back to COLOCATED prefill on the decode replicas — ``prefill_slot``
is the same graph/sampler/rng chain as detached+inject, so the
fallback stays bit-identical while ``/healthz`` reports ``degraded``.

Bit-identity: ``prefill_detached`` is the same forward graph, sampler
and rng chain as ``prefill_slot``; the block crosses the wire as raw
bytes; ``inject_slot_kv`` is the scatter ``prefill_slot`` would have
done. So a disaggregated request's tokens are bit-identical to the
colocated engine AND to per-request ``generate`` — with or without
injected faults (tier-1-gated in tests/test_serve_chaos.py).
"""
from __future__ import annotations

import itertools
import queue
import socket
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ... import rpc, telemetry
from ...base import env_float, env_int, env_str
from ...telemetry import distributed as dtrace
from ...models import llama
from ..engine import (KVHandoff, Request, ServeEngine, bucket_for,
                      cancel_counter, _env_int)
from .replica import (EngineReplica, NoHealthyReplicas, ReplicaSet,
                      Ticket)

__all__ = ["KVChannel", "PrefillWorker", "DisaggBackend",
           "CircuitBreaker"]

_HELLO = ("kvhello", "mxtpu-kv")
_HELLO_ACK = ("kvhello-ack", "mxtpu-kv")


def _channel_secret() -> bytes:
    return env_str(
        "MXTPU_GATEWAY_SECRET", "",
        "Shared secret for the gateway KV-handoff channel: every "
        "handoff frame is HMAC-SHA256-authenticated when set (the "
        "kvstore wire discipline). REQUIRED when prefill and decode "
        "pools ride TCP across hosts.").encode()


class KVChannel:
    """One framed-RPC handoff pipe. Thread-safe on both sides (many
    prefill workers share the send side; one feeder drains the
    receive side).

    TCP channels self-heal: pass ``redial`` (send side) or build the
    receive side with ``accept(..., reaccept=True)`` and a severed
    connection is re-dialed/re-accepted with backoff, re-authenticated
    via the HMAC hello handshake, and the interrupted handoff resent
    (:meth:`send_handoff` / :meth:`recv_handoff` — the ACKed, reliable
    surface the disagg pools use; raw :meth:`send`/:meth:`recv` stay
    as the unacknowledged primitive). Socketpair channels have no
    redial path and keep the fail-fast behavior."""

    def __init__(self, sock: socket.socket,
                 secret: Optional[bytes] = None, *,
                 redial: Optional[Callable[[], socket.socket]] = None,
                 listener: Optional[socket.socket] = None):
        self._sock: Optional[socket.socket] = sock
        self._secret = (_channel_secret() if secret is None
                        else secret)
        self._redial = redial
        self._listener = listener
        self._closing = False
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._retry_deadline_s = env_float(
            "MXTPU_GATEWAY_KV_RETRY_DEADLINE_S", 30.0,
            "Total reconnect+resend budget per KV-handoff frame "
            "before the prefill worker gives the request up (size it "
            "to cover a decode-host restart).")
        self._m_bytes = telemetry.histogram(
            "gateway_kv_handoff_bytes",
            "KV-handoff frame sizes on the prefill→decode channel",
            buckets=telemetry.BYTES_BUCKETS)
        self._m_count = telemetry.counter(
            "gateway_kv_handoffs_total",
            "KV blocks shipped prefill→decode")
        self._m_reconnects = telemetry.counter(
            "gateway_kv_reconnects_total",
            "KV-handoff channel reconnections (severed + re-dialed "
            "or re-accepted, HMAC re-authenticated)")
        self._m_resends = telemetry.counter(
            "gateway_kv_resends_total",
            "Handoff frames resent after a connection fault")
        self._m_frame_errors = telemetry.counter(
            "gateway_kv_frame_errors_total",
            "Torn/corrupt/unauthenticated frames dropped by the "
            "receive side (connection poisoned + re-accepted)")

    # -- construction ---------------------------------------------------------
    @classmethod
    def pair(cls, secret: Optional[bytes] = None
             ) -> Tuple["KVChannel", "KVChannel"]:
        """Same-process pair (the in-tree topology: pools as thread
        groups, handoff still through the real wire codec). No
        reconnect path — a severed socketpair is a process bug, not a
        network fault."""
        a, b = socket.socketpair()
        return cls(a, secret=secret), cls(b, secret=secret)

    @classmethod
    def listen(cls, host: str = "127.0.0.1", port: int = 0,
               secret: Optional[bytes] = None
               ) -> Tuple[socket.socket, int]:
        """Decode-side accept socket for cross-host pools; returns
        (listener, bound_port) — call :meth:`accept` next."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(8)
        return srv, srv.getsockname()[1]

    @classmethod
    def accept(cls, listener: socket.socket,
               secret: Optional[bytes] = None, *,
               reaccept: bool = False) -> "KVChannel":
        """Accept + HMAC-handshake one peer. ``reaccept=True`` keeps
        the listener on the channel: a later severed/corrupted
        connection is replaced by accepting (and re-authenticating)
        the peer's redial instead of killing the feeder."""
        sec = _channel_secret() if secret is None else secret
        conn, _ = listener.accept()
        cls._handshake_server(conn, sec)
        return cls(conn, secret=sec,
                   listener=listener if reaccept else None)

    @classmethod
    def connect(cls, host: str, port: int,
                secret: Optional[bytes] = None,
                timeout: float = 30.0) -> "KVChannel":
        """Dial + HMAC-handshake the decode side; the dialer is kept
        as the channel's ``redial`` so ``send_handoff`` can reconnect
        through a severed wire."""
        sec = _channel_secret() if secret is None else secret

        def dial() -> socket.socket:
            s = socket.create_connection((host, port), timeout=timeout)
            s.settimeout(timeout)
            return s

        sock = dial()
        cls._handshake_client(sock, sec)
        return cls(sock, secret=sec, redial=dial)

    # -- the HMAC hello handshake --------------------------------------------
    # Re-auth on every (re)connect, the PS client's heartbeat
    # discipline: a wrong-secret or foreign peer fails HERE — as
    # RPCAuthError/RPCProtocolError, which connect_with_backoff NEVER
    # retries — instead of poisoning the first real handoff.
    @staticmethod
    def _handshake_client(sock: socket.socket, secret: bytes) -> None:
        rpc.send_msg(sock, _HELLO, secret)
        reply, _ = rpc.recv_msg(sock, secret)
        if tuple(reply) != _HELLO_ACK:
            raise rpc.RPCProtocolError(
                f"peer is not an mxtpu KV-handoff endpoint: "
                f"{str(reply)[:80]}")

    @staticmethod
    def _handshake_server(sock: socket.socket, secret: bytes) -> None:
        try:
            msg, _ = rpc.recv_msg(sock, secret)
        except rpc.RPCAuthError:
            # tell the dialer its auth was REJECTED before closing: the
            # unauthenticated error frame fails the dialer's own MAC
            # check, so IT raises RPCAuthError too — both sides fail
            # fast instead of one retrying a misconfiguration forever
            try:
                rpc.send_msg(sock, ("kvhello-err", "auth"))
            except OSError:
                pass
            raise
        if tuple(msg) != _HELLO:
            raise rpc.RPCProtocolError(
                f"peer is not an mxtpu KV-handoff endpoint: "
                f"{str(msg)[:80]}")
        rpc.send_msg(sock, _HELLO_ACK, secret)

    # -- raw (unacknowledged) primitives -------------------------------------
    def send(self, msg: Any) -> None:
        with self._send_lock:
            n = rpc.send_msg(self._sock, msg, self._secret)
        self._m_bytes.observe(n)
        self._m_count.inc()

    def recv(self) -> Any:
        with self._recv_lock:
            msg, _ = rpc.recv_msg(self._sock, self._secret)
        return msg

    # -- reliable handoff surface --------------------------------------------
    def _reconnect_locked(self,
                          deadline: Optional[float] = None) -> None:
        """Send-side: re-dial + re-auth under the send lock, bounded
        by the CALLER's frame deadline when given — a fresh budget per
        reconnect attempt would let one frame's give-up time reach a
        multiple of the documented MXTPU_GATEWAY_KV_RETRY_DEADLINE_S."""
        if self._redial is None:
            raise ConnectionError(
                "kv channel severed and not re-dialable")
        if deadline is None:
            deadline = time.monotonic() + self._retry_deadline_s
        sock = rpc.connect_with_backoff(
            self._redial, deadline,
            verify=lambda s: self._handshake_client(s, self._secret))
        self._sock = sock
        self._m_reconnects.inc()
        telemetry.flight().record("gateway", "kv_reconnect")

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def send_handoff(self, msg: Any) -> None:
        """Reliable send: frame + await the receiver's ack; on a
        connection fault reconnect (backoff + HMAC re-auth) and
        RESEND. The receiver's pending-table pop dedups the
        delivered-but-unacked case. RPCAuthError propagates
        immediately — an auth failure can only repeat.

        The ack round-trip runs under the send lock, so concurrent
        prefill workers serialize at one frame per seat round-trip.
        That is deliberate: it keeps frame/ack pairing trivial under
        reconnect, and prefill COMPUTE dominates the RTT at today's
        scales. If the channel ever becomes the bottleneck, the acks
        already carry the rid — correlate them through a dispatcher
        to pipeline sends without changing the wire format."""
        deadline = time.monotonic() + self._retry_deadline_s
        sent_once = False
        while True:
            try:
                with self._send_lock:
                    if self._sock is None:
                        self._reconnect_locked(deadline)
                    n = rpc.send_msg(self._sock, msg, self._secret)
                    if sent_once:
                        self._m_resends.inc()
                    reply, _ = rpc.recv_msg(self._sock, self._secret)
                if not (isinstance(reply, tuple) and len(reply) == 2
                        and reply[0] == "kvack"):
                    raise rpc.RPCProtocolError(
                        f"expected handoff ack, got {str(reply)[:80]}")
                self._m_bytes.observe(n)
                self._m_count.inc()
                return
            except rpc.RPCAuthError:
                with self._send_lock:
                    self._drop_locked()
                raise               # secret mismatch: never retried
            except (ConnectionError, OSError) as e:
                with self._send_lock:
                    self._drop_locked()
                sent_once = True
                if self._closing or self._redial is None \
                        or time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"kv handoff not deliverable: {e}") from e
                telemetry.flight().record("gateway", "kv_send_retry",
                                          error=repr(e)[:120])

    def recv_handoff(self) -> Any:
        """Reliable receive: one verified frame, acked back to the
        sender. A torn/corrupt/misauthenticated frame on a
        re-acceptable channel poisons only the CONNECTION (drop +
        re-accept + re-auth); the sender resends. On a channel without
        a listener the error propagates (socketpair topology keeps
        the old fail-fast contract). A wrong-secret peer fails the
        re-accept handshake loudly — no retry loop."""
        while True:
            try:
                with self._recv_lock:
                    msg, _ = rpc.recv_msg(self._sock, self._secret)
                # ack on the PAYLOAD: a frame wrapped in the ISSUE-8
                # trace-context header acks exactly like a bare one
                inner, _ctx = rpc.split_context(msg)
                if (isinstance(inner, tuple) and len(inner) >= 2
                        and inner[0] in ("kv", "kverr",
                                         "kvpage", "kvdone")):
                    with self._send_lock:
                        rpc.send_msg(self._sock, ("kvack", inner[1]),
                                     self._secret)
                return msg
            except (rpc.RPCAuthError, rpc.RPCProtocolError) as e:
                # the peer AUTHENTICATED at accept time, so this is
                # wire damage or desync, not misconfiguration:
                # quarantine the connection, take the redial
                if self._closing or self._listener is None:
                    raise
                self._m_frame_errors.inc()
                telemetry.flight().record(
                    "gateway", "kv_frame_error", error=repr(e)[:120])
                self._reaccept()
            except (ConnectionError, OSError):
                if self._closing or self._listener is None:
                    raise
                self._reaccept()

    def _reaccept(self) -> None:
        with self._recv_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            conn, _ = self._listener.accept()
            # re-auth: a wrong-secret redial fails HERE, fast
            self._handshake_server(conn, self._secret)
            self._sock = conn
        self._m_reconnects.inc()
        telemetry.flight().record("gateway", "kv_reaccept")

    def close(self) -> None:
        self._closing = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()


def handoff_to_wire(rid: int, h: KVHandoff) -> tuple:
    return ("kv", int(rid), int(h.true_len), int(h.token),
            np.asarray(h.k), np.asarray(h.v),
            np.asarray(h.rng, np.uint32))


def wire_to_handoff(msg: tuple) -> Tuple[int, KVHandoff]:
    if not (isinstance(msg, tuple) and len(msg) == 7
            and msg[0] == "kv"):
        raise rpc.RPCProtocolError(
            f"not a KV-handoff frame: {str(msg)[:80]}")
    _, rid, true_len, token, k, v, rng = msg
    return int(rid), KVHandoff(k=k, v=v, true_len=int(true_len),
                               token=int(token), rng=rng)


def handoff_to_page_frames(rid: int, h: KVHandoff,
                           page_size: int) -> List[tuple]:
    """Page-granular wire encoding (the paged-KV handoff): the block
    is TRIMMED to the page multiple covering ``true_len`` — prompt-
    bucket padding never crosses the wire — and split into one
    ``kvpage`` frame per page, closed by a ``kvdone`` frame carrying
    the scalars. Each frame rides :meth:`KVChannel.send_handoff`
    (acked, resend-safe: the receiver keys chunks by index, so a
    resent page overwrites itself)."""
    k, v = np.asarray(h.k), np.asarray(h.v)
    n = min(k.shape[2], -(-int(h.true_len) // page_size) * page_size)
    frames: List[tuple] = [
        ("kvpage", int(rid), i // page_size,
         k[:, :, i:i + page_size], v[:, :, i:i + page_size])
        for i in range(0, n, page_size)]
    frames.append(("kvdone", int(rid), int(h.true_len), int(h.token),
                   np.asarray(h.rng, np.uint32), len(frames)))
    return frames


def pages_to_handoff(done: tuple,
                     parts: Dict[int, Tuple[np.ndarray, np.ndarray]]
                     ) -> Tuple[int, KVHandoff]:
    """Reassemble a page-granular handoff from its ``kvdone`` frame +
    the ``kvpage`` chunks received for that rid. A missing chunk is a
    protocol error (the acked channel should make it impossible)."""
    if not (isinstance(done, tuple) and len(done) == 6
            and done[0] == "kvdone"):
        raise rpc.RPCProtocolError(
            f"not a kvdone frame: {str(done)[:80]}")
    _, rid, true_len, token, rng, n_chunks = done
    missing = [i for i in range(int(n_chunks)) if i not in parts]
    if missing:
        raise rpc.RPCProtocolError(
            f"kv handoff rid={rid} missing page chunks {missing[:8]}")
    k = np.concatenate([parts[i][0] for i in range(int(n_chunks))],
                       axis=2)
    v = np.concatenate([parts[i][1] for i in range(int(n_chunks))],
                       axis=2)
    return int(rid), KVHandoff(k=k, v=v, true_len=int(true_len),
                               token=int(token),
                               rng=np.asarray(rng, np.uint32))


class _PageBuffer:
    """Feeder-side INCREMENTAL reassembly of a page-granular handoff:
    each kvpage frame is copied into a growing host block on arrival
    (idempotent by page index — a resent frame overwrites itself in
    place), so by the time the closing kvdone lands the block is
    already assembled and the kvdone → seat path does no
    concatenation work. With a streaming worker those copies overlap
    prefill compute; with the one-shot worker the behavior is
    unchanged except the assembly moving off the seat path. Frames
    ride an ordered acked channel, so the first frame's width IS the
    page size (only the last page may be short)."""

    __slots__ = ("k", "v", "have", "ps")

    def __init__(self):
        self.k: Optional[np.ndarray] = None
        self.v: Optional[np.ndarray] = None
        self.have: Dict[int, int] = {}      # page idx -> width
        self.ps = 0

    def add(self, idx: int, kc, vc) -> None:
        kc, vc = np.asarray(kc), np.asarray(vc)
        idx, w = int(idx), int(kc.shape[2])
        if self.ps == 0:
            self.ps = w
        elif w > self.ps:
            raise rpc.RPCProtocolError(
                f"kvpage width {w} exceeds page size {self.ps}")
        need = idx * self.ps + w
        if self.k is None or self.k.shape[2] < need:
            cap = max(need, 2 * (self.k.shape[2]
                                 if self.k is not None else 0))
            nk = np.zeros(kc.shape[:2] + (cap,) + kc.shape[3:],
                          kc.dtype)
            nv = np.zeros_like(nk)
            if self.k is not None:
                nk[:, :, :self.k.shape[2]] = self.k
                nv[:, :, :self.v.shape[2]] = self.v
            self.k, self.v = nk, nv
        off = idx * self.ps
        self.k[:, :, off:off + w] = kc
        self.v[:, :, off:off + w] = vc
        self.have[idx] = w

    def finish(self, done: tuple) -> Tuple[int, KVHandoff]:
        """Close out on the kvdone frame — same contract as
        :func:`pages_to_handoff`, minus the concatenation."""
        if not (isinstance(done, tuple) and len(done) == 6
                and done[0] == "kvdone"):
            raise rpc.RPCProtocolError(
                f"not a kvdone frame: {str(done)[:80]}")
        _, rid, true_len, token, rng, n_chunks = done
        n_chunks = int(n_chunks)
        missing = [i for i in range(n_chunks) if i not in self.have]
        if missing or n_chunks < 1:
            raise rpc.RPCProtocolError(
                f"kv handoff rid={rid} missing page chunks "
                f"{missing[:8]}")
        n = (n_chunks - 1) * self.ps + self.have[n_chunks - 1]
        return int(rid), KVHandoff(
            k=self.k[:, :, :n], v=self.v[:, :, :n],
            true_len=int(true_len), token=int(token),
            rng=np.asarray(rng, np.uint32))


class CircuitBreaker:
    """Consecutive-failure breaker over the prefill path. closed →
    normal routing; ``threshold`` consecutive failures → OPEN
    (colocated-prefill fallback, ``/healthz`` degrades); after
    ``cooldown_s`` one probe request is let through (HALF-OPEN) —
    its success closes the breaker, its failure re-opens the clock.
    Thread-safe; every transition hits
    ``gateway_breaker_transitions_total{to}`` and the flight ring."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.threshold = (threshold if threshold is not None
                          else env_int(
                              "MXTPU_GATEWAY_BREAKER_THRESHOLD", 3,
                              "Consecutive prefill-path failures "
                              "(worker deaths, failed jobs, channel "
                              "give-ups) that trip the disagg "
                              "circuit breaker into colocated-"
                              "prefill fallback."))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else env_float(
                               "MXTPU_GATEWAY_BREAKER_COOLDOWN_S",
                               30.0,
                               "Seconds an OPEN disagg breaker waits "
                               "before letting one half-open probe "
                               "request test the prefill pool."))
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self.trips = 0
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self._m: Dict[str, Any] = {}

    def _transition(self, to: str) -> None:
        self._state = to
        m = self._m.get(to)
        if m is None:
            m = self._m[to] = telemetry.counter(
                "gateway_breaker_transitions_total",
                "Disagg circuit-breaker state transitions", to=to)
        m.inc()
        telemetry.flight().record("gateway", "breaker", state=to,
                                  failures=self._failures)

    def allow(self) -> bool:
        """True → use the prefill pool; False → colocated fallback.
        An OPEN breaker past its cooldown grants exactly ONE half-open
        probe per cooldown window."""
        with self._lock:
            if self._state == "closed":
                return True
            now = self._clock()
            if self._state == "open" \
                    and now - self._opened_at >= self.cooldown_s:
                self._half_open_at = now
                self._transition("half_open")
                return True          # the one probe
            if self._state == "half_open" \
                    and now - self._half_open_at >= self.cooldown_s:
                # the last probe never resolved (cancelled mid-
                # prefill, client gone): re-grant rather than strand
                # the breaker in half_open forever
                self._half_open_at = now
                return True
            return False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open" \
                    or (self._state == "closed"
                        and self._failures >= self.threshold):
                self._opened_at = self._clock()
                self.trips += 1
                self._transition("open")

    def record_success(self) -> None:
        with self._lock:
            if self._state == "open":
                # a straggler handoff submitted BEFORE the trip: its
                # success says nothing about the pool now — hold open
                # for the cooldown and let the half-open probe decide,
                # else the breaker flaps on every in-flight leftover
                return
            self._failures = 0
            if self._state != "closed":
                self._transition("closed")

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "trips": self.trips,
                    "threshold": self.threshold}


class PrefillWorker:
    """One prefill compute thread: pops (rid, Request) jobs, runs the
    bucketed ``prefill_detached`` program, host-gathers the block (the
    sync IS this pool's job — decode never blocks on it) and ships it
    over the channel. ``current()`` + ``drain()`` expose the in-flight
    and queued jobs so the pool can respawn a dead worker and resubmit
    its work (DataLoader's dead-worker pattern)."""

    def __init__(self, cfg, params, channel: KVChannel, *,
                 min_bucket: int, max_len: int, mesh=None,
                 name: str = "p0",
                 on_fail: Optional[Callable[[int, str],
                                            None]] = None,
                 wire_page_size: Optional[int] = None,
                 stream_chunk: Optional[int] = None):
        self.cfg = cfg
        self.params = params
        self.channel = channel
        self.min_bucket = min_bucket
        self.max_len = max_len
        # page-granular handoff (paged decode pool): ship the block as
        # one acked frame per KV page, trimmed to the pages true_len
        # covers — bucket padding never crosses the wire
        self.wire_page_size = wire_page_size
        # streamed prefill pages: compute the prompt in fixed-width
        # chunks and ship each page's frame AS IT FILLS, so wire
        # transfer + feeder staging overlap prefill compute instead of
        # trailing it (TTFT). Rounded up to a power of two so every
        # chunk divides every bucket (one compiled chunk program per
        # bucket); requires a power-of-two wire page so page frames
        # align with chunk boundaries — anything else falls back to
        # the one-shot path silently.
        sc = (stream_chunk if stream_chunk is not None else env_int(
            "MXTPU_DISAGG_STREAM_CHUNK", 0,
            "Chunk width (tokens) for streamed detached prefill: the "
            "prefill worker runs the prompt in chunks of this many "
            "tokens and ships each KV page's wire frame as its page "
            "fills, overlapping handoff transfer with compute "
            "(rounded up to a power of two >= the wire page size); "
            "0 keeps the one-shot prefill, where every page ships at "
            "completion."))
        self.stream_chunk = 0
        if sc and wire_page_size and not (int(wire_page_size)
                                          & (int(wire_page_size) - 1)):
            cw = 1
            while cw < max(int(sc), int(wire_page_size)):
                cw *= 2
            self.stream_chunk = cw
        self.mesh = mesh
        self.name = name
        self.on_fail = on_fail
        self.stopping = False
        self.failure: Optional[BaseException] = None
        self._fns: Dict[int, Any] = {}
        self._cfns: Dict[int, Any] = {}
        self._jobs: "queue.Queue[Any]" = queue.Queue()
        self._cur_lock = threading.Lock()
        self._current: Optional[Tuple[int, Request]] = None
        self._span = telemetry.span_factory("gateway.prefill",
                                            "gateway_prefill")
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mxtpu-gw-prefill-{name}")
        self._thread.start()

    def submit(self, rid: int, req: Request) -> None:
        self._jobs.put((rid, req))

    def pending(self) -> int:
        return self._jobs.qsize()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def current(self) -> Optional[Tuple[int, Request]]:
        with self._cur_lock:
            return self._current

    def drain(self) -> List[Tuple[int, Request]]:
        """Pull every queued job off a (dead) worker for
        resubmission."""
        out: List[Tuple[int, Request]] = []
        while True:
            try:
                job = self._jobs.get_nowait()
            except queue.Empty:
                return out
            if job is not None:
                out.append(job)

    def stop(self, join: bool = True, timeout: float = 60.0) -> None:
        self.stopping = True
        self._jobs.put(None)
        if join:
            self._thread.join(timeout)

    @property
    def compile_count(self) -> int:
        return int(sum(f._cache_size() for f in self._fns.values())
                   + sum(f._cache_size()
                         for f in self._cfns.values()))

    def _fn(self, bucket: int):
        fn = self._fns.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.prefill_detached, self.cfg,
                                mesh=self.mesh)),
                f"gateway_prefill_b{bucket}", expected=1)
            self._fns[bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int):
        """The streamed-prefill chunk program for one bucket (chunk
        width is fixed per worker, so this is one compile per bucket
        — the same growth rate as the one-shot prefill). The running
        cache is donated: chunk c+1 reuses chunk c's buffers."""
        fn = self._cfns.get(bucket)
        if fn is None:
            fn = telemetry.watch(
                jax.jit(partial(llama.prefill_detached_chunk,
                                self.cfg, mesh=self.mesh),
                        donate_argnums=(2,)),
                f"gateway_prefill_stream_b{bucket}", expected=1)
            self._cfns[bucket] = fn
        return fn

    def _run(self) -> None:
        """Thread body: an exception escaping the job loop (a chaos
        kill, an unexpected device fault) is a worker DEATH — recorded
        so ``check_pools`` can tell a crash from a drain and respawn."""
        try:
            self._loop()
        except BaseException as e:   # noqa: BLE001 — reported to pool
            self.failure = e
            telemetry.flight().record(
                "gateway", "prefill_worker_died", worker=self.name,
                error=repr(e)[:200])

    def _loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            with self._cur_lock:
                self._current = job
            # cleared only on normal return: an exception escaping
            # _one kills the worker, and the job it died holding IS
            # what check_pools must hand to the replacement
            self._one(*job)
            with self._cur_lock:
                self._current = None

    def _one(self, rid: int, req: Request) -> None:
        # this hop gets its own trace segment; the handoff frame
        # carries it across the wire (versioned rpc context header),
        # so a decode host in ANOTHER process continues the trace
        ctx = getattr(req, "ctx", None)
        if ctx is not None:
            ctx = ctx.child()
        try:
            prompt = np.asarray(req.prompt, np.int32).reshape(-1)
            bucket = bucket_for(prompt.size, self.min_bucket,
                                self.max_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :prompt.size] = prompt
            V = self.cfg.vocab_size
            # device-commit a resume chain (numpy key != PRNGKey
            # device array in the jit cache — engine.py has the story)
            key = (jax.random.PRNGKey(req.seed) if req.rng is None  # noqa: MXL301 — chain position 0 is PRNGKey(seed); the rng branch is a mid-chain resume key
                   else jax.numpy.asarray(np.asarray(req.rng,
                                                     np.uint32)))
            if self.stream_chunk and self.wire_page_size:
                with dtrace.use(ctx), self._span(bucket=bucket,
                                                 worker=self.name):
                    self._one_streamed(rid, req, padded,
                                       int(prompt.size), bucket,
                                       key, ctx)
                return
            with dtrace.use(ctx), self._span(bucket=bucket,
                                             worker=self.name):
                tok, kb, vb, rng = self._fn(bucket)(
                    self.params, padded, np.int32(prompt.size),
                    key,
                    np.float32(req.temperature),
                    np.int32(V if req.top_k is None
                             else req.top_k),
                    np.float32(1.0 if req.top_p is None
                               else req.top_p))
            h = KVHandoff(k=np.asarray(kb), v=np.asarray(vb),
                          true_len=int(prompt.size),
                          token=int(np.asarray(tok)[0]),
                          rng=np.asarray(rng, np.uint32))
            if self.wire_page_size:
                # the trace context rides the CLOSING frame — that is
                # the one the feeder seats from
                for frame in handoff_to_page_frames(
                        rid, h, int(self.wire_page_size)):
                    if ctx is not None and frame[0] == "kvdone":
                        frame = rpc.attach_context(frame,
                                                   ctx.to_wire())
                    self.channel.send_handoff(frame)
            else:
                frame = handoff_to_wire(rid, h)
                if ctx is not None:
                    frame = rpc.attach_context(frame, ctx.to_wire())
                self.channel.send_handoff(frame)
        except rpc.RPCAuthError:
            raise                   # misconfiguration: die loudly
        except (ConnectionError, OSError) as e:
            if self.stopping:
                raise               # pool shutdown: exit via _run
            # the channel gave up on THIS frame (reconnect budget
            # burned): fail the request, keep serving — the breaker
            # decides whether the pool as a whole is still viable
            telemetry.counter(
                "gateway_prefill_errors_total",
                "Prefill jobs that failed on a worker").inc()
            telemetry.flight().record("gateway", "handoff_failed",
                                      rid=rid, worker=self.name,
                                      error=repr(e)[:200])
            if self.on_fail is not None:
                self.on_fail(rid, "error")
        except Exception as e:
            # a failed prefill (device error, bad state) must not
            # kill the worker and strand every later request: the
            # error frame lets the feeder finalize THIS rid and
            # the loop keeps serving
            telemetry.counter(
                "gateway_prefill_errors_total",
                "Prefill jobs that failed on a worker").inc()
            telemetry.flight().record("gateway", "prefill_error",
                                      rid=rid, worker=self.name,
                                      error=repr(e)[:200])
            try:
                self.channel.send_handoff(("kverr", int(rid),
                                           repr(e)[:200]))
            except (ConnectionError, OSError):
                # the error report itself is undeliverable: finalize
                # locally so the request still ends exactly once —
                # letting this escape would kill the worker with the
                # POISONED job still marked in-flight, and check_pools
                # would re-run the very prefill that just failed
                if self.on_fail is not None:
                    self.on_fail(rid, "error")

    def _one_streamed(self, rid: int, req: Request, padded, true_len,
                      bucket: int, key, ctx) -> None:
        """Streamed prefill: run the prompt in ``stream_chunk``-wide
        slices of :func:`llama.prefill_detached_chunk` and ship each
        chunk's kvpage frames from a dedicated SHIPPER thread while
        the compute loop moves on to the next chunk. The thread is
        what makes the overlap real: host gather, wire serialize and
        NIC occupancy all release the GIL, and the compute loop never
        waits on the wire even where the backend's dispatch is
        synchronous (CPU). Bit-identical to the one-shot path: same
        causal math per position, same single rng split (the chunk
        program's contract), same wire frames in the same order —
        only their timing changes. The closing kvdone is sent after
        the shipper drains, and carries the final chunk's token/rng
        and the trace context, exactly like the one-shot sender."""
        ps = int(self.wire_page_size)
        cw = min(self.stream_chunk, bucket)
        # every page that carries prompt tokens, capped at the bucket
        # (same trim rule as handoff_to_page_frames)
        n_send = min(bucket, -(-true_len // ps) * ps)
        cfg = self.cfg
        shape = (cfg.n_layers, 1, cfg.n_kv_heads, bucket,
                 cfg.head_dim)
        # two distinct buffers: the chunk program donates the cache,
        # and one zeros array aliased as both k and v cannot be
        # donated twice
        cache = {"k": jax.numpy.zeros(shape, cfg.dtype),
                 "v": jax.numpy.zeros(shape, cfg.dtype),
                 "pos": jax.numpy.zeros((), jax.numpy.int32)}
        V = cfg.vocab_size
        temp = np.float32(req.temperature)
        tk = np.int32(V if req.top_k is None else req.top_k)
        tp = np.float32(1.0 if req.top_p is None else req.top_p)
        tok = rng_out = None
        # unbounded on purpose: worst case it holds the full block on
        # host, exactly what the one-shot gather does anyway — and an
        # unbounded put can never deadlock against a dead shipper
        todo: "queue.Queue" = queue.Queue()
        shipped = []
        fault: list = []

        def _shipper():
            while True:
                item = todo.get()
                if item is None:
                    return
                try:
                    shipped.append(
                        self._ship_pages(rid, *item, n_send))
                except BaseException as e:      # noqa: BLE001 — must
                    fault.append(e)             # cross the thread seam
                    return

        shipper = threading.Thread(target=_shipper, daemon=True,
                                   name="mxtpu-kv-shipper")
        shipper.start()
        for pos in range(0, n_send, cw):
            t, kc, vc, r, cache = self._chunk_fn(bucket)(
                self.params, padded[:, pos:pos + cw], cache,
                np.int32(true_len), key, temp, tk, tp)
            if pos <= true_len - 1 < pos + cw:
                tok, rng_out = t, r
            todo.put((pos, kc, vc))
        todo.put(None)
        shipper.join()
        if fault:
            raise fault[0]
        n_frames = sum(shipped)
        telemetry.counter(
            "gateway_prefill_stream_jobs_total",
            "Prefill jobs served by the streamed (chunked) path").inc()
        done = ("kvdone", int(rid), int(true_len),
                int(np.asarray(tok)[0]),
                np.asarray(rng_out, np.uint32), n_frames)
        if ctx is not None:
            done = rpc.attach_context(done, ctx.to_wire())
        self.channel.send_handoff(done)

    def _ship_pages(self, rid: int, pos: int, kc, vc,
                    n_send: int) -> int:
        """Host-gather one computed chunk and send a kvpage frame per
        page it fills (short final page when the bucket is smaller
        than a page, same as the one-shot encoder). Returns the frame
        count."""
        k, v = np.asarray(kc), np.asarray(vc)
        ps = int(self.wire_page_size)
        sent = 0
        for off in range(0, k.shape[2], ps):
            if pos + off >= n_send:
                break
            end = min(off + ps, n_send - pos)
            self.channel.send_handoff(
                ("kvpage", int(rid), (pos + off) // ps,
                 k[:, :, off:end], v[:, :, off:end]))
            sent += 1
        return sent


class DisaggBackend:
    """Prefill pool + decode replicas + the feeder joining them — the
    same routing surface ``ReplicaSet`` gives the Gateway (including
    the supervisor's ``replicas``/``remove_replica``/``spawn_replica``,
    which operate on the DECODE pool). The autoscaler's ``scale_to``
    also moves the decode pool; the prefill pool is sized at
    construction and kept at size by ``check_pools`` respawn."""

    def __init__(self, cfg, params, *, n_prefill: int = 1,
                 n_decode: int = 1, max_slots: int = 4,
                 max_len: Optional[int] = None,
                 min_bucket: Optional[int] = None, mesh=None,
                 channel: Optional[Tuple[KVChannel, KVChannel]] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 clock=None, started: bool = True,
                 paged: bool = False,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 int8_pages: Optional[bool] = None,
                 kv_journal: Optional[int] = None,
                 stream_chunk: Optional[int] = None):
        max_len = int(max_len or cfg.max_seq_len)
        min_bucket = int(min_bucket or 16)
        self._cfg = cfg
        self._params = params
        self._mesh = mesh
        self._min_bucket = min_bucket
        self._mlen = max_len
        # paged decode pool: page-granular wire + journaled handoffs
        self.paged = bool(paged)
        self._wire_ps = (int(page_size
                             or _env_int("MXTPU_KV_PAGE_SIZE", 16))
                         if self.paged else None)
        tx, rx = channel if channel is not None else KVChannel.pair()
        self._tx, self._rx = tx, rx
        self.decode = ReplicaSet(
            lambda: ServeEngine(cfg, params, max_slots=max_slots,
                                max_len=max_len, min_bucket=min_bucket,
                                mesh=mesh, clock=clock,
                                paged=paged, page_size=page_size,
                                n_pages=n_pages,
                                prefix_cache=prefix_cache,
                                int8_pages=int8_pages),
            n_decode, started=started)
        # feeder-thread-only reassembly buffers: rid -> _PageBuffer
        # (each kvpage frame is copied into the buffer on arrival, so
        # seating at kvdone does no assembly work)
        self._parts: Dict[int, _PageBuffer] = {}
        self._stream_chunk = stream_chunk
        # KV journal (paged re-dispatch seam): the last N seated
        # handoffs, keyed by their prompt tokens — a crash re-dispatch
        # whose prompt EXTENDS a journaled one re-seats the pages and
        # warm-prefills only the emitted suffix, instead of burning a
        # prefill-worker pass on the whole prompt.
        # COST: each entry pins a FULL host K/V block (layers x
        # kv_heads x bucket x head_dim, k + v) — hundreds of MB on
        # production-sized models — so the real bound is BYTES, not
        # entries: oldest entries fall off once the total crosses
        # MXTPU_GATEWAY_KV_JOURNAL_MB (kv_journal still caps the
        # entry count; 0 for either disables the journal).
        cap = (kv_journal if kv_journal is not None
               else (32 if self.paged else 0))
        self._journal_cap = max(0, int(cap))
        self._journal_max_bytes = max(0, env_int(
            "MXTPU_GATEWAY_KV_JOURNAL_MB", 256,
            "Total host-RAM byte budget (in MB) for the gateway's "
            "seated-handoff KV journal; a single block larger than "
            "the budget is not journaled at all.")) * (1 << 20)
        self._journal_bytes = 0
        self._journal: "Dict[Tuple[int, ...], KVHandoff]" = {}
        self._m_journal_hits = telemetry.counter(
            "gateway_kv_journal_hits_total",
            "Crash re-dispatches seated from the KV journal (paged "
            "inject + suffix warm prefill, no full re-prefill)")
        self._m_page_frames = telemetry.counter(
            "gateway_kv_page_frames_total",
            "kvpage frames received on the page-granular handoff wire")
        self._wseq = itertools.count()
        self.prefill: List[PrefillWorker] = [
            self._new_worker() for _ in range(max(1, n_prefill))]
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker(clock=clock)
        self._m_wrestarts = telemetry.counter(
            "gateway_prefill_restarts_total",
            "Prefill workers respawned after dying")
        self._m_fallback = telemetry.counter(
            "gateway_breaker_fallback_total",
            "Requests served via colocated prefill while the disagg "
            "breaker was open")
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._seq = itertools.count()
        # rid -> (request, ticket, submit time on self._clock)
        self._pending: Dict[int, Tuple[Request, "_DisaggTicket",
                                       float]] = {}
        # rids whose job was already resubmitted once after a worker
        # death — a second death on the same rid fails the request
        # (the DataLoader discipline: respawn + resubmit ONCE)
        self._resubmitted: set = set()
        self._feeder = threading.Thread(target=self._feed, daemon=True,
                                        name="mxtpu-gw-kv-feeder")
        self._feeder.start()

    def _new_worker(self) -> PrefillWorker:
        return PrefillWorker(
            self._cfg, self._params, self._tx,
            min_bucket=self._min_bucket, max_len=self._mlen,
            mesh=self._mesh, name=f"p{next(self._wseq)}",
            on_fail=self._fail_pending,
            wire_page_size=self._wire_ps,
            stream_chunk=self._stream_chunk)

    def _fail_pending(self, rid: int, reason: str = "error") -> None:
        """Finalize a pending request whose prefill/handoff failed
        terminally (pops the pending table so load_total and the
        admission bound stop charging for it)."""
        self.breaker.record_failure()
        with self._lock:
            entry = self._pending.pop(rid, None)
            self._resubmitted.discard(rid)
        if entry is not None:
            self._count_cancel(reason)
            if entry[0].on_done is not None:
                entry[0].on_done(rid, reason)

    # -- KV journal (paged re-dispatch) --------------------------------------
    @staticmethod
    def _handoff_nbytes(h: KVHandoff) -> int:
        return int(np.asarray(h.k).nbytes) + int(np.asarray(h.v).nbytes)

    def _journal_put(self, prompt: np.ndarray,
                     handoff: KVHandoff) -> None:
        if self._journal_cap <= 0 or self._journal_max_bytes <= 0:
            return
        nb = self._handoff_nbytes(handoff)
        if nb > self._journal_max_bytes:
            return      # one block alone busts the budget: skip it
        key = tuple(int(t) for t in prompt)
        with self._lock:
            old = self._journal.pop(key, None)  # refresh insert order
            if old is not None:
                self._journal_bytes -= self._handoff_nbytes(old)
            self._journal[key] = handoff
            self._journal_bytes += nb
            while self._journal and (
                    len(self._journal) > self._journal_cap
                    or self._journal_bytes > self._journal_max_bytes):
                ev = self._journal.pop(next(iter(self._journal)))
                self._journal_bytes -= self._handoff_nbytes(ev)

    def _journal_lookup(self, prompt: np.ndarray
                        ) -> Optional[KVHandoff]:
        """Longest journaled prompt that is a STRICT prefix of
        ``prompt`` — the re-dispatch prompt is ``original + emitted``,
        so the original's handoff matches here."""
        pt = tuple(int(t) for t in prompt)
        with self._lock:
            best = None
            for key, h in self._journal.items():
                if (len(key) < len(pt) and pt[:len(key)] == key
                        and (best is None
                             or len(key) > best[0])):
                    best = (len(key), h)
            return best[1] if best is not None else None

    # -- Gateway surface -----------------------------------------------------
    def route(self, req: Request, handoff=None) -> "Ticket":
        if handoff is not None:
            return self.decode.route(req, handoff=handoff)
        if self.paged and req.rng is not None \
                and self._journal_cap > 0:
            # a resume chain (crash re-dispatch): if the journal holds
            # the original prompt's pages, seat them directly — the
            # engine injects the pages and warm-prefills only the
            # emitted suffix; bit-identical (same rng chain) but no
            # prefill-pool round trip
            rp = np.asarray(req.prompt, np.int32).reshape(-1)
            jh = self._journal_lookup(rp)
            if jh is not None and int(rp.size) + int(
                    req.max_new_tokens) <= self._mlen:
                self._m_journal_hits.inc()
                telemetry.flight().record(
                    "gateway", "kv_journal_hit",
                    prefix=int(jh.true_len), prompt=int(rp.size))
                return self.decode.route(req, handoff=jh)
        # validate NOW (the prefill thread can only log, not raise to
        # the caller) — same checks ServeEngine.submit applies
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{req.max_new_tokens}")
        if prompt.size + req.max_new_tokens > self._max_len():
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_len")
        if req.top_k is not None and req.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {req.top_k}")
        if req.top_p is not None and not 0.0 < req.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got "
                             f"{req.top_p}")
        if not self.breaker.allow():
            # OPEN breaker: colocated fallback — the decode engine
            # runs prefill_slot itself (same graph/sampler/rng chain,
            # so tokens stay bit-identical); latency degrades, the
            # request does not
            self._m_fallback.inc()
            return self.decode.route(req)
        ticket = _DisaggTicket(self)
        # pick + submit under the SAME lock check_pools swaps workers
        # under: an unsynchronized pick could land the job on a dead
        # worker's queue just after its replacement drained it
        with self._lock:
            worker = min((w for w in self.prefill if w.alive),
                         key=lambda w: w.pending(), default=None)
            if worker is not None:
                rid = next(self._seq)
                ticket.rid = rid
                self._pending[rid] = (req, ticket, self._clock())
                worker.submit(rid, req)
        if worker is None:
            # whole pool down between check_pools passes: fall back
            # rather than queue onto a corpse
            self.breaker.record_failure()
            self._m_fallback.inc()
            return self.decode.route(req)
        return ticket

    def load_total(self) -> Dict[str, int]:
        out = self.decode.load_total()
        with self._lock:
            out["queued"] += len(self._pending)
        return out

    def state(self) -> List[Dict[str, Any]]:
        with self._lock:
            n_pending = len(self._pending)
        return ([dict(name=w.name, role="prefill", alive=w.alive,
                      healthy=w.alive and not w.stopping,
                      failed=w.failure is not None,
                      error=(repr(w.failure)[:120] if w.failure
                             else None),
                      queued=w.pending(), active=0, slots=0)
                 for w in self.prefill]
                + [dict(r, role="decode")
                   for r in self.decode.state()]
                + [dict(name="handoff", role="channel", alive=True,
                        queued=n_pending, active=0, slots=0,
                        paged=self.paged,
                        kv_journal=len(self._journal),
                        kv_journal_bytes=int(self._journal_bytes),
                        breaker=self.breaker.describe())])

    # -- supervisor surface (decode pool) ------------------------------------
    def replicas(self) -> List[EngineReplica]:
        return self.decode.replicas()

    def remove_replica(self, replica: EngineReplica) -> bool:
        return self.decode.remove_replica(replica)

    def spawn_replica(self) -> Optional[EngineReplica]:
        return self.decode.spawn_replica()

    def breaker_state(self) -> Dict[str, Any]:
        return self.breaker.describe()

    def check_pools(self) -> int:
        """The prefill half of supervision (called from the gateway's
        maintenance loop): respawn dead workers and resubmit their
        jobs ONCE — the in-flight job plus everything queued behind
        it. A job whose SECOND worker also died is failed with reason
        ``error`` (it is probably what killed them). Returns the
        number of workers respawned."""
        respawned = 0
        for i in range(len(self.prefill)):
            # capture + swap under the routing lock so a concurrent
            # route() can never submit onto the corpse after we
            # drained it
            with self._lock:
                w = self.prefill[i]
                if w.alive or w.stopping:
                    continue
                jobs = ([w.current()]
                        if w.current() is not None else []) \
                    + w.drain()
                fresh = self._new_worker()
                self.prefill[i] = fresh
            respawned += 1
            self._m_wrestarts.inc()
            self.breaker.record_failure()
            telemetry.flight().record(
                "gateway", "prefill_respawn", worker=w.name,
                replacement=fresh.name, jobs=len(jobs),
                error=(repr(w.failure)[:120] if w.failure else None))
            for rid, req in jobs:
                with self._lock:
                    second = rid in self._resubmitted
                    if not second:
                        self._resubmitted.add(rid)
                if second:
                    self._fail_pending(rid, "error")
                else:
                    fresh.submit(rid, req)
        return respawned

    @property
    def size(self) -> int:
        return self.decode.size

    def scale_to(self, n: int) -> int:
        return self.decode.scale_to(n)

    def start(self) -> None:
        self.decode.start()

    def close(self) -> None:
        for w in self.prefill:
            w.stop(join=True)
        self._tx.close()
        self._rx.close()
        self._feeder.join(10.0)
        self.decode.close()

    # -- internals -----------------------------------------------------------
    def _max_len(self) -> int:
        return self._mlen

    @staticmethod
    def _count_cancel(reason: str) -> None:
        cancel_counter(reason).inc()

    def _feed(self) -> None:
        while True:
            try:
                msg = self._rx.recv_handoff()
            except (ConnectionError, OSError):
                return                      # channel closed: shutdown
            # frames from an ISSUE-8 sender carry the trace context
            # in the versioned header; older frames split to (msg,
            # None) and everything below behaves exactly as before
            msg, wire_ctx = rpc.split_context(msg)
            if (isinstance(msg, tuple) and len(msg) == 3
                    and msg[0] == "kverr"):
                rid, err = int(msg[1]), msg[2]
                self._parts.pop(rid, None)   # orphaned page chunks
                self.breaker.record_failure()
                with self._lock:
                    entry = self._pending.pop(rid, None)
                    self._resubmitted.discard(rid)
                if entry is not None and entry[0].on_done is not None:
                    entry[0].on_done(rid, "error")
                if entry is not None:
                    self._count_cancel("error")
                continue
            if (isinstance(msg, tuple) and len(msg) == 5
                    and msg[0] == "kvpage"):
                # one page of an in-flight handoff: copied into the
                # rid's assembly buffer NOW (idempotent — a resent
                # chunk overwrites itself in place), so seating at
                # kvdone starts from a finished block
                try:
                    self._parts.setdefault(
                        int(msg[1]), _PageBuffer()).add(
                            int(msg[2]), msg[3], msg[4])
                except rpc.RPCProtocolError as e:
                    telemetry.flight().record(
                        "gateway", "kv_channel_error",
                        error=repr(e)[:200])
                    return
                self._m_page_frames.inc()
                continue
            try:
                if (isinstance(msg, tuple) and msg
                        and msg[0] == "kvdone"):
                    buf = self._parts.pop(int(msg[1]), None)
                    rid, handoff = (buf if buf is not None
                                    else _PageBuffer()).finish(msg)
                else:
                    rid, handoff = wire_to_handoff(msg)
            except rpc.RPCProtocolError as e:
                # a foreign frame means the stream is desynced — stop
                # feeding loudly rather than seat corrupt state
                telemetry.flight().record("gateway", "kv_channel_error",
                                          error=repr(e)[:200])
                return
            with self._lock:
                entry = self._pending.pop(rid, None)
                self._resubmitted.discard(rid)
                reason = (entry[1].cancelled_reason
                          if entry is not None else None)
            if entry is None:
                continue    # cancelled while prefilling, or a resent
                #             duplicate whose first copy already seated
            req, ticket, t_submit = entry
            if getattr(req, "ctx", None) is None and wire_ctx:
                # cross-process decode host: the request object was
                # rebuilt here, so the trace identity arrives on the
                # WIRE — adopt it and the engine's seat/done events
                # join the same trace
                try:
                    req.ctx = dtrace.TraceContext.from_wire(wire_ctx)
                except ValueError:
                    pass
            with dtrace.use(getattr(req, "ctx", None)):
                telemetry.instant("gateway.handoff_recv",
                                  true_len=int(handoff.true_len))
            self.breaker.record_success()
            if reason is None and req.deadline_s is not None:
                # the budget started at SUBMIT, not at seating: a
                # request that burned it queued behind prefill expires
                # here, and a survivor decodes on the REMAINDER
                elapsed = self._clock() - t_submit
                if elapsed >= req.deadline_s:
                    reason = "deadline"
                else:
                    req.deadline_s = req.deadline_s - elapsed
            if reason is not None:
                self._count_cancel(reason)
                if req.on_done is not None:
                    req.on_done(rid, reason)
                continue
            seated = self._seat_with_retry(req, handoff)
            if seated is None:
                self._count_cancel("error")
                if req.on_done is not None:
                    req.on_done(rid, "error")
                continue
            # the journal keeps the seated handoff's host bytes: a
            # decode-replica crash re-seats THESE pages instead of
            # re-running the whole prompt through the prefill pool
            self._journal_put(
                np.asarray(req.prompt, np.int32).reshape(-1), handoff)
            with self._lock:
                ticket.seated = seated
                reason = ticket.cancelled_reason
            if reason is not None:          # cancel raced the seating
                seated.cancel(reason)

    def _seat_with_retry(self, req: Request, handoff: KVHandoff,
                         budget_s: Optional[float] = None):
        """Seat a handoff in the decode pool, riding out a transient
        zero-healthy window (a decode replica down, its replacement
        still in spawn backoff). The feeder thread must NEVER die on
        this — a dead feeder acks nothing and wedges the whole
        prefill pool. Returns None when seating is truly impossible
        (budget burned, invalid state): the caller fails that one
        request and keeps feeding. The budget runs on the backend's
        injected clock (deterministic under a fake-clock test) and
        defaults to the same per-frame retry knob as the channel."""
        if budget_s is None:
            budget_s = self._tx._retry_deadline_s
        deadline = self._clock() + budget_s
        while True:
            try:
                return self.decode.route(req, handoff=handoff)
            except NoHealthyReplicas:
                if self._clock() >= deadline:
                    telemetry.flight().record(
                        "gateway", "seat_failed", reason="no_replica")
                    return None
                time.sleep(0.05)
            except (ValueError, RuntimeError) as e:
                telemetry.flight().record(
                    "gateway", "seat_failed", error=repr(e)[:120])
                return None


class _DisaggTicket:
    """Cancellation handle across the two phases: before the handoff
    lands the request only exists in ``_pending`` (cancel = drop +
    fire on_done); after seating it is a decode-engine rid."""

    def __init__(self, backend: DisaggBackend):
        self._backend = backend
        self.rid: Optional[int] = None
        self.seated: Optional[Ticket] = None
        self.cancelled_reason: Optional[str] = None

    def on_replica(self, replica: EngineReplica) -> bool:
        """Supervision filter: this request rides ``replica`` once its
        handoff has seated there (pre-seating it belongs to the
        prefill pool, whose failures are handled by check_pools)."""
        return self.seated is not None \
            and self.seated.on_replica(replica)

    def dead(self) -> bool:
        return self.seated is not None and self.seated.dead()

    def cancel(self, reason: str = "cancel") -> bool:
        with self._backend._lock:
            if self.seated is not None:
                seated = self.seated
            else:
                # pending (or mid-handoff): the feeder checks the
                # reason under this same lock before/after seating
                self.cancelled_reason = reason
                entry = self._backend._pending.pop(self.rid, None)
                seated = None
        if seated is not None:
            return seated.cancel(reason)
        if entry is None:
            return True          # feeder will honor cancelled_reason
        req = entry[0]
        self._backend._count_cancel(reason)
        if req.on_done is not None:
            req.on_done(self.rid, reason)
        return True
