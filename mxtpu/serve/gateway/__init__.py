"""Multi-replica serving tier over ``ServeEngine`` (docs/serving.md
§gateway): an HTTP front door with admission control and token
streaming, a replica manager with least-loaded routing and
deadline/cancel plumbing, a disaggregated prefill/decode mode with a
framed-RPC KV handoff (``mxtpu.rpc`` — the kvstore wire layer), and a
telemetry-driven autoscaler.

    from mxtpu.serve.gateway import Gateway
    gw = Gateway(lambda: ServeEngine(cfg, params, ...), n_replicas=2)
    port = gw.start_http()
    # POST /v1/generate streams tokens; GET /metrics is Prometheus

Disaggregated (DistServe-style) topology:

    from mxtpu.serve.gateway import DisaggBackend
    gw = Gateway(backend=DisaggBackend(cfg, params, n_prefill=2,
                                       n_decode=2, max_slots=8))

The routing/streaming contract preserves the engine's bit-identity
guarantee end to end: tokens through the gateway — replicated or
disaggregated — equal per-request ``llama.generate``. That guarantee
extends THROUGH failures (docs/robustness.md §serving): a supervisor
restarts dead/stalled replicas and the gateway re-dispatches their
in-flight requests past the already-streamed prefix with the rng
chain fast-forwarded, so a crash-surviving stream is bit-identical to
a fault-free one; the disagg KV channel reconnects + re-auths and a
circuit breaker falls back to colocated prefill under sustained
prefill failure.
"""
from .autoscale import AutoscalePolicy, Autoscaler
from .disagg import (CircuitBreaker, DisaggBackend, KVChannel,
                     PrefillWorker)
from .frontdoor import GatewayClient
from .gateway import (PRIORITIES, Gateway, GatewayOverloaded,
                      GatewayUnavailable, RequestHandle)
from .replica import (EngineReplica, GatewayClosed, NoHealthyReplicas,
                      ReplicaSet, ReplicaSupervisor, Ticket)

__all__ = ["Gateway", "GatewayOverloaded", "GatewayUnavailable",
           "GatewayClosed", "RequestHandle", "GatewayClient",
           "EngineReplica", "ReplicaSet", "ReplicaSupervisor",
           "NoHealthyReplicas", "Ticket", "DisaggBackend",
           "KVChannel", "PrefillWorker", "CircuitBreaker",
           "AutoscalePolicy", "Autoscaler", "PRIORITIES"]
