"""Telemetry-driven autoscaling: the loop that turns the PR 5 gauges
into replica counts.

Signals (all already exported by the serving stack — the scaler adds
no instrumentation of its own):

- **pressure**: the backend's un-seated request count per replica
  (``load_total()["queued"] / size`` — the same number the
  ``serve_queue_depth`` gauges carry, read at the source so a fake
  backend makes tests deterministic);
- **latency**: interval p99 of ``serve_token_latency_ms`` — each tick
  diffs the process-wide histogram's cumulative buckets against the
  previous tick and interpolates the percentile inside the window, so
  the target tracks CURRENT latency, not the run's history;
- **slack**: slot occupancy (``active / slots``).

Policy (deliberately boring — hysteresis over cleverness):

- scale UP one replica when per-replica queue pressure exceeds
  ``queue_high`` or interval p99 exceeds ``target_p99_ms``;
- scale DOWN one replica when the queue is empty AND occupancy is
  under ``occupancy_low`` AND latency is in budget, sustained for
  ``cooldown_s``;
- never within ``cooldown_s`` of the last decision, never outside
  [``min_replicas``, ``max_replicas``].

Every decision increments ``gateway_scale_events_total{direction}``
and lands in the flight recorder with the signal values that drove it
— an unexplained replica count is a grep, not an archaeology session.
The loop is a pure function of (clock, signals): tests drive
:meth:`Autoscaler.tick` with a fake clock and injected loads.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ... import telemetry
from ...telemetry.registry import interval_percentile
from .replica import GatewayClosed

__all__ = ["AutoscalePolicy", "Autoscaler", "interval_p99"]


@dataclass
class AutoscalePolicy:
    min_replicas: int = 1
    max_replicas: int = 4
    target_p99_ms: float = 0.0       # 0 = ignore the latency signal
    queue_high: float = 2.0          # un-seated requests per replica
    occupancy_low: float = 0.25      # scale-down ceiling
    cooldown_s: float = 10.0         # min gap between decisions AND
    #                                  sustained-idle requirement
    interval_s: float = 1.0          # loop period

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"bad replica bounds [{self.min_replicas}, "
                f"{self.max_replicas}]")


def interval_p99(bounds, prev_counts: Optional[List[int]],
                 counts: List[int], q: float = 99.0) -> Optional[float]:
    """Windowed p99 between two cumulative-bucket snapshots. The
    bucket-diff math moved to ``telemetry.registry
    .interval_percentile`` when the SLO gauges became its second
    consumer (ISSUE 8 satellite: one copy, shared); this name stays
    as the autoscaler's established alias."""
    return interval_percentile(bounds, prev_counts, counts, q)


class Autoscaler:
    """Drives ``pool.scale_to`` from the serving telemetry.

    ``pool``: ``size``, ``load_total() -> {queued, active, slots}``,
    ``scale_to(n)`` — a ``ReplicaSet``, a ``DisaggBackend`` (scales
    its decode pool), or a test fake. ``latency_p99``: optional
    override returning the current-window p99 ms (None = read the
    process-wide ``serve_token_latency_ms`` histogram)."""

    def __init__(self, pool, policy: AutoscalePolicy, *,
                 clock: Optional[Callable[[], float]] = None,
                 latency_p99: Optional[Callable[[], Optional[float]]]
                 = None):
        self.pool = pool
        self.policy = policy
        self._clock = clock or time.monotonic
        self._latency_override = latency_p99
        self._last_counts: Optional[List[int]] = None
        self._last_scale: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_p99: Optional[float] = None
        self._m_events: Dict[str, object] = {}
        self.decisions: List[Dict] = []       # bounded: see tick()

    def _count_event(self, direction: str) -> None:
        m = self._m_events.get(direction)
        if m is None:
            m = self._m_events[direction] = telemetry.counter(
                "gateway_scale_events_total",
                "Autoscaler decisions, by direction",
                direction=direction)
        m.inc()

    def _window_p99(self) -> Optional[float]:
        if self._latency_override is not None:
            return self._latency_override()
        h = telemetry.registry().get("serve_token_latency_ms")
        if h is None:
            return None
        counts, _, _ = h.snapshot()
        prev, self._last_counts = self._last_counts, counts
        return interval_p99(h.bounds, prev, counts)

    def tick(self) -> Optional[str]:
        """One decision pass; returns "up"/"down"/None."""
        pol = self.policy
        now = self._clock()
        n = self.pool.size
        load = self.pool.load_total()
        pressure = load["queued"] / max(1, n)
        occupancy = load["active"] / max(1, load["slots"])
        p99 = self._window_p99()
        self._last_p99 = p99
        in_cooldown = (self._last_scale is not None
                       and now - self._last_scale < pol.cooldown_s)

        hot = (pressure > pol.queue_high
               or (pol.target_p99_ms > 0 and p99 is not None
                   and p99 > pol.target_p99_ms))
        idle = (load["queued"] == 0 and occupancy < pol.occupancy_low
                and not hot)
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        direction = None
        if hot and n < pol.max_replicas and not in_cooldown:
            direction = "up"
        elif (idle and n > pol.min_replicas and not in_cooldown
              and self._idle_since is not None
              and now - self._idle_since >= pol.cooldown_s):
            direction = "down"
        if direction is None:
            return None

        new_n = n + (1 if direction == "up" else -1)
        try:
            self.pool.scale_to(new_n)
        except GatewayClosed:
            # a late tick racing close(): the pool refused loudly
            # (uniform close semantics) — stand down, count nothing
            return None
        self._last_scale = now
        self._idle_since = None
        self._count_event(direction)
        record = {"t": now, "direction": direction, "from": n,
                  "to": new_n, "pressure": round(pressure, 3),
                  "occupancy": round(occupancy, 3),
                  "p99_ms": None if p99 is None else round(p99, 2)}
        telemetry.flight().record("gateway", "scale", **record)
        self.decisions.append(record)
        del self.decisions[:-64]       # bounded decision log
        return direction

    def describe(self) -> Dict:
        """Live policy + last-signal snapshot (GET /state)."""
        return {"replicas": self.pool.size,
                "min": self.policy.min_replicas,
                "max": self.policy.max_replicas,
                "target_p99_ms": self.policy.target_p99_ms,
                "last_p99_ms": self._last_p99,
                "decisions": self.decisions[-5:]}

    def run_forever(self, stop: threading.Event) -> None:
        while not stop.wait(self.policy.interval_s):
            try:
                self.tick()
            except Exception:
                # a scaling hiccup must never kill the loop — the
                # flight ring has the signals, the next tick retries
                telemetry.flight().record("gateway", "scale_error")
