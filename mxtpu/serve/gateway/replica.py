"""Engine replicas: N ``ServeEngine`` workers behind one router.

A replica is a ``ServeEngine`` plus the thread running its
``run_forever`` loop. The set routes each request to the least-loaded
live replica (queued + active, normalized by slot count — occupancy
routing, not round-robin: a replica stuck behind a long decode keeps
its queue short instead of stacking latecomers). ``scale_to`` is the
autoscaler's lever: scaling up starts fresh replicas from the factory;
scaling down REMOVES a replica from routing and signals its stop event
— the drained engine finishes every accepted request before its thread
exits, so a scale-down never drops work.

Tokens are a per-request property of the engine (each slot replays its
own rng chain), so replication/routing cannot change output — the
gateway-level bit-identity test in tests/test_gateway.py pins this
across 2 replicas under a Poisson client stream.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional

from ... import telemetry
from ..engine import KVHandoff, Request, ServeEngine

__all__ = ["EngineReplica", "ReplicaSet", "Ticket"]


class Ticket:
    """A routed request: where it landed and how to cancel it — the
    opaque handle Gateway keeps per in-flight request."""

    def __init__(self, replica: "EngineReplica", rid: int):
        self.replica = replica
        self.rid = rid

    def cancel(self, reason: str = "cancel") -> bool:
        return self.replica.cancel(self.rid, reason)


class EngineReplica:
    """One serving engine on its own daemon thread."""

    def __init__(self, engine: ServeEngine, name: str = "r0"):
        self.engine = engine
        # a replica serves indefinitely: results flow through the
        # on_token/on_done callbacks, so the engine must prune its
        # per-request bookkeeping instead of retaining it forever
        engine.retain_results = False
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self.engine.run_forever, args=(self._stop,),
            daemon=True, name=f"mxtpu-gw-{self.name}")
        self._thread.start()

    def submit(self, req: Request) -> int:
        return self.engine.submit(req)

    def submit_prefilled(self, handoff: KVHandoff, req: Request) -> int:
        return self.engine.submit_prefilled(handoff, req)

    def cancel(self, rid: int, reason: str) -> bool:
        return self.engine.cancel(rid, reason)

    def load(self) -> Dict[str, int]:
        return self.engine.load()

    def stop(self, join: bool = False, timeout: float = 60.0) -> None:
        """Signal the loop to drain and exit; ``join=True`` waits."""
        self._stop.set()
        self.engine.wake()
        if join and self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


class ReplicaSet:
    """The colocated-serving backend: replicas + least-loaded routing
    + the ``scale_to`` surface the autoscaler drives."""

    def __init__(self, engine_factory: Callable[[], ServeEngine],
                 n_replicas: int = 1, *, started: bool = True):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self._factory = engine_factory
        self._lock = threading.Lock()
        self._closed = False
        self._replicas: List[EngineReplica] = []
        self._draining: List[EngineReplica] = []
        self._seq = itertools.count()
        self._started = started
        self._m_replicas = telemetry.gauge(
            "gateway_replicas", "Live engine replicas behind the "
            "gateway router")
        self.scale_to(n_replicas)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start every replica loop (a set built with
        ``started=False`` — tests that need a stalled backend — starts
        here)."""
        with self._lock:
            self._started = True
            for r in self._replicas:
                r.start()

    def close(self, timeout: float = 60.0) -> None:
        with self._lock:
            self._closed = True
            reps = self._replicas + self._draining
            self._replicas, self._draining = [], []
        for r in reps:
            r.stop()
        for r in reps:
            if r._thread is not None:
                r._thread.join(timeout)
        self._m_replicas.set(0)

    # -- routing -----------------------------------------------------------
    def route(self, req: Request,
              handoff: Optional[KVHandoff] = None) -> Ticket:
        """Submit to the least-loaded replica. Raises RuntimeError
        after ``close()``. Pick + submit are ONE critical section:
        concurrent routes must see each other's submissions (two
        racing requests both reading queued=0 would pile onto the
        same replica), and a route racing close() must never hand a
        request to a replica nothing will serve."""
        with self._lock:
            if self._closed or not self._replicas:
                raise RuntimeError("replica set is closed")
            loads = [(r, r.load()) for r in self._replicas]
            replica, _ = min(
                loads, key=lambda rl: (rl[1]["queued"]
                                       + rl[1]["active"])
                / max(1, rl[1]["slots"]))
            rid = (replica.submit(req) if handoff is None
                   else replica.submit_prefilled(handoff, req))
        return Ticket(replica, rid)

    # -- autoscaler surface ------------------------------------------------
    @property
    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def scale_to(self, n: int) -> int:
        """Grow/shrink to ``n`` live replicas (floor 1). Shrinking
        moves replicas to the draining list — out of routing
        immediately, threads exit once their accepted work is done."""
        n = max(1, int(n))
        with self._lock:
            if self._closed:
                # a late autoscaler tick racing close() must never
                # resurrect replicas nothing will ever stop
                return 0
            while len(self._replicas) < n:
                r = EngineReplica(self._factory(),
                                  name=f"r{next(self._seq)}")
                if self._started:
                    r.start()
                self._replicas.append(r)
            drained = []
            while len(self._replicas) > n:
                drained.append(self._replicas.pop())
            self._draining.extend(drained)
            self._draining = [d for d in self._draining if d.alive]
            live = len(self._replicas)
        for d in drained:
            d.stop()
        self._m_replicas.set(live)
        return live

    # -- introspection ------------------------------------------------------
    def load_total(self) -> Dict[str, int]:
        out = {"queued": 0, "active": 0, "slots": 0}
        with self._lock:
            reps = list(self._replicas)
        for r in reps:
            ld = r.load()
            for k in out:
                out[k] += ld[k]
        return out

    def state(self) -> List[Dict[str, Any]]:
        with self._lock:
            reps = list(self._replicas)
        return [dict(name=r.name, alive=r.alive, **r.load())
                for r in reps]
