"""Engine replicas: N ``ServeEngine`` workers behind one router, plus
the supervision layer that keeps the set serving through replica
failure.

A replica is a ``ServeEngine`` plus the thread running its
``run_forever`` loop. The set routes each request to the least-loaded
HEALTHY replica (queued + active, normalized by slot count — occupancy
routing, not round-robin: a replica stuck behind a long decode keeps
its queue short instead of stacking latecomers). ``scale_to`` is the
autoscaler's lever: scaling up starts fresh replicas from the factory;
scaling down REMOVES a replica from routing and signals its stop event
— the drained engine finishes every accepted request before its thread
exits, so a scale-down never drops work.

Failure model (the PR 7 robustness layer):

- a replica thread that DIES (an exception escaping the engine loop —
  a device error mid-decode, a chaos-injected raise) records its
  exception on the replica and flips :attr:`EngineReplica.failed`;
- a replica that STALLS (thread alive, work pending, but the engine's
  step counter stops advancing) is detected by the
  :class:`ReplicaSupervisor`'s step-progress heartbeat;
- either way the supervisor pulls the replica out of routing, spins up
  a replacement (bounded restarts + exponential backoff, every event
  in ``gateway_replica_restarts_total{reason}`` and the flight
  recorder), and hands the dead replica's in-flight requests back to
  the gateway for deterministic re-dispatch (``gateway.py``).

``route`` with zero healthy replicas raises
:class:`NoHealthyReplicas` — a DISTINCT error the front door turns
into 503 + ``Retry-After`` (shed loudly, never hang a client on a
backend nothing will serve).

Tokens are a per-request property of the engine (each slot replays its
own rng chain), so replication/routing/restart cannot change output —
the chaos tests in tests/test_serve_chaos.py pin bit-identity through
an injected replica kill under a Poisson client stream.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ... import telemetry
from ...base import env_float, env_int
from ..engine import KVHandoff, Request, ServeEngine

__all__ = ["EngineReplica", "ReplicaSet", "ReplicaSupervisor",
           "Ticket", "NoHealthyReplicas", "GatewayClosed"]


class NoHealthyReplicas(RuntimeError):
    """``route`` found no live replica to carry the request (all dead
    or removed, restart budget exhausted, or the set is empty). The
    front door maps this to 503 + ``Retry-After`` — distinct from
    queue overload (429) and from a closed set
    (:class:`GatewayClosed`): the client should retry later, not
    slower."""


class GatewayClosed(RuntimeError):
    """The pool has been ``close()``d: every mutating surface
    (``route``, ``scale_to``, ``drain_replica``) raises this — one
    consistent refusal instead of the old mix of a plain RuntimeError
    on route and a silent no-op on scale_to. Subclasses RuntimeError
    so callers that already caught the closed-set RuntimeError (the
    gateway's submit path, supervisor races) keep working unchanged;
    loops that tick on a timer (autoscaler, fleet arbiter) catch it
    by name and stand down."""


class Ticket:
    """A routed request: where it landed and how to cancel it — the
    opaque handle Gateway keeps per in-flight request."""

    def __init__(self, replica: "EngineReplica", rid: int):
        self.replica = replica
        self.rid = rid

    def cancel(self, reason: str = "cancel") -> bool:
        return self.replica.cancel(self.rid, reason)

    def on_replica(self, replica: "EngineReplica") -> bool:
        """True when this request's fate is tied to ``replica`` — the
        supervisor's re-dispatch filter."""
        return self.replica is replica

    def dead(self) -> bool:
        """The carrying replica FAILED (crash/stall takedown — never a
        drain, which finishes its work): the gateway's periodic sweep
        re-dispatches journal entries this returns True for, catching
        a death that raced ticket registration."""
        return self.replica.failed


class EngineReplica:
    """One serving engine on its own daemon thread."""

    def __init__(self, engine: ServeEngine, name: str = "r0"):
        self.engine = engine
        # a replica serves indefinitely: results flow through the
        # on_token/on_done callbacks, so the engine must prune its
        # per-request bookkeeping instead of retaining it forever
        engine.retain_results = False
        # per-request trace events name the REPLICA, not "engine":
        # a crash-resumed request's timeline must show both banks
        engine.role = name
        self.name = name
        self.failed = False
        # model-build tag (fleet pools stamp this at spawn; the
        # response's `version` field and version-aware re-dispatch
        # read it). None for plain single-build sets.
        self.version: Optional[str] = None
        self.failure: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mxtpu-gw-{self.name}")
        self._thread.start()

    def _run(self) -> None:
        """Thread body: an exception escaping the engine loop is a
        replica DEATH, not a silent thread exit — record it so the
        supervisor (and /state) can tell a crash from a drain."""
        try:
            self.engine.run_forever(self._stop)
        except BaseException as e:   # noqa: BLE001 — reported via state
            self.failure = e
            self.failed = True
            telemetry.flight().record(
                "gateway", "replica_died", replica=self.name,
                error=repr(e)[:200])

    def submit(self, req: Request) -> int:
        return self.engine.submit(req)

    def submit_prefilled(self, handoff: KVHandoff, req: Request) -> int:
        return self.engine.submit_prefilled(handoff, req)

    def cancel(self, rid: int, reason: str) -> bool:
        return self.engine.cancel(rid, reason)

    def load(self) -> Dict[str, int]:
        return self.engine.load()

    def stop(self, join: bool = False, timeout: float = 60.0) -> None:
        """Signal the loop to drain and exit; ``join=True`` waits."""
        self._stop.set()
        self.engine.wake()
        if join and self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def healthy(self) -> bool:
        """Routable: not failed, and its thread either hasn't started
        yet (``started=False`` sets — work queues until ``start()``)
        or is still running and not draining."""
        if self.failed:
            return False
        if self._thread is None:
            return not self._stop.is_set()
        return self._thread.is_alive() and not self._stop.is_set()

    def heartbeat(self) -> Dict[str, Any]:
        """The supervisor's step-progress probe (one snapshot, no
        lock-ordering risk: every field is read through the engine's
        own lock or is a plain attribute)."""
        ld = self.load()
        return {"name": self.name, "alive": self.alive,
                "healthy": self.healthy, "failed": self.failed,
                "steps": self.engine.steps_run,
                "work": ld["queued"] + ld["active"]}


class ReplicaSet:
    """The colocated-serving backend: replicas + least-loaded routing
    + the ``scale_to`` surface the autoscaler drives + the
    remove/spawn surface the supervisor drives."""

    def __init__(self, engine_factory: Callable[[], ServeEngine],
                 n_replicas: int = 1, *, started: bool = True,
                 name_prefix: str = "r",
                 labels: Optional[Dict[str, str]] = None):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self._factory = engine_factory
        self._lock = threading.Lock()
        self._closed = False
        self._replicas: List[EngineReplica] = []
        self._draining: List[EngineReplica] = []
        self._seq = itertools.count()
        self._started = started
        # fleet pools prefix with the model name so federated scrapes
        # and /state rows are attributable without a join; `labels`
        # (e.g. model=<name>) keeps two pools' replica gauges from
        # last-write-clobbering each other in one registry
        self._name_prefix = name_prefix
        self._m_replicas = telemetry.gauge(
            "gateway_replicas", "Live engine replicas behind the "
            "gateway router", **dict(labels or {}))
        self.scale_to(n_replicas)

    def _new_replica(self) -> EngineReplica:
        """Build (never start/register) one replica from the factory —
        the ONE construction point ``scale_to`` and ``spawn_replica``
        share, so a subclass stamping per-build metadata (fleet pools
        set ``.version``) covers every spawn path. Called under
        ``_lock``."""
        return EngineReplica(
            self._factory(),
            name=f"{self._name_prefix}{next(self._seq)}")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start every replica loop (a set built with
        ``started=False`` — tests that need a stalled backend — starts
        here)."""
        with self._lock:
            self._started = True
            for r in self._replicas:
                r.start()

    def close(self, timeout: float = 60.0) -> None:
        with self._lock:
            self._closed = True
            reps = self._replicas + self._draining
            self._replicas, self._draining = [], []
        for r in reps:
            r.stop()
        for r in reps:
            if r._thread is not None:
                r._thread.join(timeout)
        self._m_replicas.set(0)

    # -- routing -----------------------------------------------------------
    def route(self, req: Request,
              handoff: Optional[KVHandoff] = None, *,
              prefer: Optional[str] = None,
              version: Optional[str] = None) -> Ticket:
        """Submit to the least-loaded healthy replica. Raises
        :class:`GatewayClosed` after ``close()`` and
        :class:`NoHealthyReplicas` when every replica is
        dead/removed. Pick + submit are ONE critical section:
        concurrent routes must see each other's submissions (two
        racing requests both reading queued=0 would pile onto the
        same replica), and a route racing close() must never hand a
        request to a replica nothing will serve.

        ``prefer``: a replica NAME — session affinity. When that
        replica is still healthy the request lands on it regardless
        of load (the session's KV-warm replica beats a cold
        least-loaded one); gone or draining, routing falls back to
        least-loaded silently.

        ``version``: restrict to replicas of one model build —
        crash re-dispatch during a hot-swap uses it so a request
        accepted on the old build resumes on the old build
        (bit-identity). Best-effort: when NO healthy replica of that
        version survives, all healthy replicas are eligible (the
        response's version label shows the seam)."""
        with self._lock:
            if self._closed:
                raise GatewayClosed("replica set is closed")
            live = [r for r in self._replicas if r.healthy]
            if not live:
                raise NoHealthyReplicas(
                    f"no healthy replica to route to "
                    f"({len(self._replicas)} registered)")
            if version is not None:
                same = [r for r in live if r.version == version]
                if not same:
                    # old-build resume mid-swap with every same-build
                    # replica already DRAINING: a draining replica
                    # still serves work submitted before it goes idle
                    # (the engine loop exits only at stop+empty), so
                    # extend one drain rather than resume on the new
                    # build and break bit-identity
                    same = [r for r in self._draining
                            if r.version == version and r.alive
                            and not r.failed]
                if same:
                    live = same
            replica = None
            if prefer is not None:
                replica = next((r for r in live if r.name == prefer),
                               None)
            if replica is None:
                loads = [(r, r.load()) for r in live]
                replica, _ = min(
                    loads, key=lambda rl: (rl[1]["queued"]
                                           + rl[1]["active"])
                    / max(1, rl[1]["slots"]))
            rid = (replica.submit(req) if handoff is None
                   else replica.submit_prefilled(handoff, req))
        return Ticket(replica, rid)

    # -- supervisor surface -------------------------------------------------
    def replicas(self) -> List[EngineReplica]:
        """Routing-set snapshot (supervision + introspection)."""
        with self._lock:
            return list(self._replicas)

    def remove_replica(self, replica: EngineReplica) -> bool:
        """Pull a dead/stalled replica out of routing WITHOUT
        replacing it (the supervisor decides whether/when to respawn).
        Returns False if it was not in the routing set (already
        removed — supervision races are benign)."""
        with self._lock:
            if replica not in self._replicas:
                return False
            self._replicas.remove(replica)
            live = len(self._replicas)
        # a stalled replica may still be running: signal its loop so
        # that even if it unwedges it drains instead of serving a
        # request the gateway has already re-dispatched elsewhere
        replica.stop()
        self._m_replicas.set(live)
        return True

    def spawn_replica(self) -> Optional[EngineReplica]:
        """Start one fresh replica from the factory and add it to
        routing (the supervisor's restart lever). None after close —
        a supervisor heartbeat racing shutdown is benign, so this one
        surface stays a quiet refusal rather than raising."""
        with self._lock:
            if self._closed:
                return None
            r = self._new_replica()
            if self._started:
                r.start()
            self._replicas.append(r)
            live = len(self._replicas)
        self._m_replicas.set(live)
        return r

    def drain_replica(self, replica: EngineReplica) -> bool:
        """Pull a HEALTHY replica out of routing and let it finish
        every accepted request before its thread exits — the hot-swap
        retirement path. Unlike the supervisor's ``remove_replica``
        (crash path: marks the replica failed so its tickets read
        dead and re-dispatch), a drained replica stays healthy to the
        requests it already holds; it just takes no new ones. The
        drained replica joins ``_draining`` so ``close()`` still
        joins its thread. Raises :class:`GatewayClosed` after
        close(); returns False when the replica was not in the
        routing set (already drained/removed)."""
        with self._lock:
            if self._closed:
                raise GatewayClosed("replica set is closed")
            if replica not in self._replicas:
                return False
            self._replicas.remove(replica)
            self._draining.append(replica)
            live = len(self._replicas)
        replica.stop()
        self._m_replicas.set(live)
        return True

    # -- autoscaler surface ------------------------------------------------
    @property
    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def set_factory(self, engine_factory: Callable[[], ServeEngine],
                    version: Optional[str] = None) -> None:
        """Swap the engine factory every FUTURE spawn uses (hot-swap:
        the new build's factory goes in first, then old replicas are
        drained one by one). Existing replicas are untouched."""
        with self._lock:
            if self._closed:
                raise GatewayClosed("replica set is closed")
            self._factory = engine_factory
            if version is not None:
                self.version = version

    def scale_to(self, n: int) -> int:
        """Grow/shrink to ``n`` live replicas (floor 1). Shrinking
        moves replicas to the draining list — out of routing
        immediately, threads exit once their accepted work is done.
        Raises :class:`GatewayClosed` after ``close()`` — scaling a
        closed pool used to return 0 silently, leaving a late
        autoscaler/arbiter believing it had capacity it did not."""
        n = max(1, int(n))
        with self._lock:
            if self._closed:
                raise GatewayClosed("replica set is closed")
            while len(self._replicas) < n:
                r = self._new_replica()
                if self._started:
                    r.start()
                self._replicas.append(r)
            drained = []
            while len(self._replicas) > n:
                drained.append(self._replicas.pop())
            self._draining.extend(drained)
            self._draining = [d for d in self._draining if d.alive]
            live = len(self._replicas)
        for d in drained:
            d.stop()
        self._m_replicas.set(live)
        return live

    # -- introspection ------------------------------------------------------
    def load_total(self) -> Dict[str, int]:
        out = {"queued": 0, "active": 0, "slots": 0}
        for r in self.replicas():
            ld = r.load()
            for k in out:
                out[k] += ld[k]
        return out

    def state(self) -> List[Dict[str, Any]]:
        return [dict(name=r.name, alive=r.alive, healthy=r.healthy,
                     failed=r.failed, version=r.version,
                     error=(repr(r.failure)[:120] if r.failure
                            else None), steps=r.engine.steps_run,
                     kv_cache=r.engine.kv_cache_stats(),
                     **r.load())
                for r in self.replicas()]


class ReplicaSupervisor:
    """Health-checks every replica via step-progress heartbeats and
    keeps the set serving: a DEAD replica (thread exited with its stop
    event clear — an escaped exception) or a STALLED one (work
    pending, step counter frozen past ``stall_s``) is pulled out of
    routing, counted in ``gateway_replica_restarts_total{reason}``,
    replaced from the factory under a bounded-restart + exponential
    backoff budget, and reported to ``on_down(replica, reason)`` — the
    gateway's deterministic re-dispatch hook.

    The loop itself is clock-injectable and single-steppable
    (:meth:`check`), so chaos tests drive it deterministically; the
    background thread (:meth:`run_forever`) is the production mode.
    """

    def __init__(self, backend, *,
                 on_down: Optional[Callable[[EngineReplica, str],
                                            None]] = None,
                 heartbeat_s: Optional[float] = None,
                 stall_s: Optional[float] = None,
                 warmup_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.backend = backend
        self.on_down = on_down
        self.heartbeat_s = (heartbeat_s if heartbeat_s is not None
                            else env_float(
                                "MXTPU_GATEWAY_HEARTBEAT_S", 0.25,
                                "Replica supervisor health-check "
                                "period (seconds)."))
        self.stall_s = (stall_s if stall_s is not None
                        else env_float(
                            "MXTPU_GATEWAY_STALL_S", 30.0,
                            "A replica with pending work whose engine "
                            "step counter does not advance for this "
                            "many seconds is declared stalled and "
                            "replaced."))
        self.warmup_s = (warmup_s if warmup_s is not None
                         else env_float(
                             "MXTPU_GATEWAY_WARMUP_STALL_S", 120.0,
                             "Stall threshold applied while a replica "
                             "has completed ZERO steps: first "
                             "admission legitimately blocks on "
                             "prefill+decode compiles, so declaring a "
                             "compiling replica stalled would kill "
                             "every replacement mid-warmup forever."))
        self.max_restarts = (max_restarts if max_restarts is not None
                             else env_int(
                                 "MXTPU_GATEWAY_MAX_RESTARTS", 5,
                                 "Replica restarts the supervisor "
                                 "will perform over the gateway's "
                                 "life before refusing further "
                                 "replacements (a crash loop must "
                                 "become a loud 503, not an infinite "
                                 "respawn)."))
        self.backoff_base_s = (
            backoff_base_s if backoff_base_s is not None
            else env_float(
                "MXTPU_GATEWAY_RESTART_BACKOFF_S", 0.05,
                "Initial delay before a replica replacement, doubled "
                "per consecutive restart (decays back after a quiet "
                "period)."))
        self.backoff_max_s = (
            backoff_max_s if backoff_max_s is not None
            else env_float(
                "MXTPU_GATEWAY_RESTART_BACKOFF_MAX", 5.0,
                "Replica-replacement backoff ceiling (seconds)."))
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        # keyed by replica NAME (unique per set, never reused — id()
        # can be recycled by the allocator after a scale-down, which
        # would hand a fresh replica a stale stall window)
        self._progress: Dict[str, tuple] = {}   # name -> (steps, t)
        self._m_restarts: Dict[str, Any] = {}
        self.restarts = 0
        self.history: List[Dict[str, Any]] = []   # bounded, /state
        self._pending_spawns = 0
        self._next_spawn_at = 0.0
        self._consecutive = 0
        self._last_down_t = 0.0

    def _count(self, reason: str) -> None:
        m = self._m_restarts.get(reason)
        if m is None:
            m = self._m_restarts[reason] = telemetry.counter(
                "gateway_replica_restarts_total",
                "Replica replacements by the gateway supervisor, "
                "by failure reason", reason=reason)
        m.inc()

    # -- detection -----------------------------------------------------------
    def _diagnose(self, replica: EngineReplica,
                  now: float) -> Optional[str]:
        hb = replica.heartbeat()
        if replica._stop.is_set():
            return None                     # draining — expected exit
        if replica._thread is not None and not hb["alive"]:
            return "died"
        key = replica.name
        last = self._progress.get(key)
        if last is None or last[0] != hb["steps"]:
            self._progress[key] = (hb["steps"], now)
            return None
        # a replica mid-warmup (zero completed steps) is most likely
        # COMPILING its admission/decode programs, not wedged — hold
        # it to the (much larger) warmup threshold instead
        limit = (self.stall_s if hb["steps"] > 0
                 else max(self.stall_s, self.warmup_s))
        if hb["work"] > 0 and hb["alive"] \
                and now - last[1] >= limit:
            return "stalled"
        if hb["work"] == 0:
            # idle is not a stall: restart the progress window
            self._progress[key] = (hb["steps"], now)
        return None

    def check(self) -> List[str]:
        """One supervision pass; returns the reasons of any replicas
        taken down this pass. Thread-safe, callable from tests."""
        now = self._clock()
        downs: List[tuple] = []
        with self._lock:
            reps = self.backend.replicas()
            seen = {r.name for r in reps}
            for stale in [k for k in self._progress
                          if k not in seen]:
                # drained via scale_to (never passed through
                # _take_down): drop its window or the dict grows
                # forever under autoscaler churn
                del self._progress[stale]
            for r in reps:
                reason = self._diagnose(r, now)
                if reason is not None:
                    downs.append((r, reason))
        for replica, reason in downs:
            self._take_down(replica, reason, now)
        self._maybe_spawn(now)
        if not downs:
            with self._lock:
                # decay the consecutive-failure count only after a
                # QUIET period (no takedown for a full backoff
                # ceiling): a serial crash loop — each replacement
                # dying right after its spawn — must keep doubling,
                # while one crash a day must not creep toward the max
                if (self._consecutive and self._pending_spawns == 0
                        and now - self._last_down_t
                        >= self.backoff_max_s):
                    self._consecutive = 0
        return [reason for _, reason in downs]

    def _take_down(self, replica: EngineReplica, reason: str,
                   now: float) -> None:
        if not self.backend.remove_replica(replica):
            return                          # raced another pass
        replica.failed = True               # never routable again
        self._count(reason)
        telemetry.flight().record(
            "gateway", "replica_down", replica=replica.name,
            reason=reason,
            error=(repr(replica.failure)[:200] if replica.failure
                   else None))
        with self._lock:
            # the window pop shares _lock with _diagnose's iteration —
            # an unlocked pop here raced the next check() pass
            self._progress.pop(replica.name, None)
            self.history.append(
                {"t": now, "replica": replica.name, "reason": reason,
                 "error": (repr(replica.failure)[:120]
                           if replica.failure else None)})
            del self.history[:-32]
            self._last_down_t = now
            if self.restarts < self.max_restarts:
                self.restarts += 1
                self._pending_spawns += 1
                delay = min(
                    self.backoff_base_s * (2 ** self._consecutive),
                    self.backoff_max_s)
                self._consecutive += 1
                self._next_spawn_at = max(self._next_spawn_at,
                                          now + delay)
            else:
                telemetry.flight().record(
                    "gateway", "restart_budget_exhausted",
                    replica=replica.name, max=self.max_restarts)
        if self.on_down is not None:
            self.on_down(replica, reason)

    def _maybe_spawn(self, now: float) -> None:
        """Replace taken-down replicas once their backoff expires (the
        backoff delays the SPAWN, never the re-dispatch — stranded
        requests move to surviving replicas immediately)."""
        while True:
            with self._lock:
                if self._pending_spawns <= 0 \
                        or now < self._next_spawn_at:
                    return
                self._pending_spawns -= 1
            fresh = self.backend.spawn_replica()
            if fresh is not None:
                telemetry.flight().record("gateway", "replica_spawned",
                                          replica=fresh.name)

    @property
    def exhausted(self) -> bool:
        """No replacement is coming: the restart budget is spent and
        nothing is pending — parked re-dispatches should fail loudly
        instead of waiting for a replica that will never exist."""
        with self._lock:
            return (self.restarts >= self.max_restarts
                    and self._pending_spawns == 0)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {"restarts": self.restarts,
                    "max_restarts": self.max_restarts,
                    "pending_spawns": self._pending_spawns,
                    "history": list(self.history[-8:])}

    def run_forever(self, stop: threading.Event) -> None:
        while not stop.wait(self.heartbeat_s):
            try:
                self.check()
            except Exception:
                # supervision must never die quietly; the flight ring
                # has the event, the next heartbeat retries
                telemetry.flight().record("gateway", "supervise_error")
