"""The gateway: admission control + routing + streaming handles over a
replica backend (colocated ``ReplicaSet`` or disaggregated
``DisaggBackend``), with the HTTP front door layered on top
(``frontdoor.py``) and the autoscaler driving ``backend.scale_to``
(``autoscale.py``). docs/serving.md has the topology diagram.

Admission control is a bounded queue over the BACKEND's un-seated
request count: once ``queued >= queue_max`` a new submission raises
:class:`GatewayOverloaded` (the front door turns it into HTTP 429 +
``Retry-After``) instead of growing an unbounded backlog whose every
entry would miss its latency target anyway — load shedding at the
door, the DistServe/Orca serving-tier discipline.

Streaming: the engine's ``on_token`` callback feeds a per-request
:class:`RequestHandle` queue and NEVER blocks — a slow HTTP consumer
stalls its own socket writer thread, not the decode loop. The
slow-client defense is the deadline: every request carries one
(explicit, or ``MXTPU_GATEWAY_DEADLINE_S``), and an expired request
frees its slot at the next step boundary.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ... import telemetry
from ...base import env_float, env_int
from ..engine import Request, ServeEngine
from .replica import ReplicaSet, Ticket

__all__ = ["Gateway", "GatewayOverloaded", "RequestHandle"]

_DONE = object()     # stream sentinel


class GatewayOverloaded(RuntimeError):
    """Admission refused: the gateway queue is at its bound. Carries
    the ``retry_after`` hint (seconds) the front door sends back."""

    def __init__(self, depth: int, bound: int, retry_after: int):
        super().__init__(
            f"gateway queue full ({depth} >= {bound}); "
            f"retry in ~{retry_after}s")
        self.depth = depth
        self.bound = bound
        self.retry_after = retry_after


class RequestHandle:
    """One submitted request as the client sees it: a thread-safe
    token stream plus the final reason (``complete`` / ``cancel`` /
    ``deadline`` / ``disconnect``)."""

    def __init__(self, gateway: "Gateway", submitted_at: float):
        self._gw = gateway
        self._submitted_at = submitted_at
        self._first_at: Optional[float] = None
        self._q: "queue.Queue[Any]" = queue.Queue()
        self._done = threading.Event()
        self.tokens: list = []
        self.reason: Optional[str] = None
        self.ticket: Optional[Ticket] = None

    # engine-side callbacks (never block: queue puts + list appends)
    def _on_token(self, rid: int, token: int) -> None:
        if self._first_at is None:
            self._first_at = time.perf_counter()
            self._gw._m_ttft.observe(
                1e3 * (self._first_at - self._submitted_at))
        self.tokens.append(int(token))
        self._q.put(int(token))

    def _on_done(self, rid: int, reason: str) -> None:
        self.reason = reason
        self._done.set()
        self._q.put(_DONE)

    # client side
    def stream(self, timeout: Optional[float] = 300.0):
        """Yield tokens as they are produced; returns when the request
        ends (``.reason`` is set by then)."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _DONE:
                return
            yield item

    def result(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        """Block until the request ends; returns the generated tokens
        (partial if cancelled — check ``.reason``)."""
        if not self._done.wait(timeout):
            raise TimeoutError("request did not finish in time")
        return np.asarray(self.tokens, np.int32)

    def cancel(self, reason: str = "cancel") -> bool:
        if self.ticket is None:
            return False
        return self.ticket.cancel(reason)


class Gateway:
    """The serving front door over engine replicas.

    ``backend`` is anything with ``route(req, handoff=None) -> Ticket``,
    ``load_total()``, ``state()``, ``size``, ``scale_to(n)``,
    ``start()`` and ``close()`` — ``ReplicaSet`` (colocated) or
    ``DisaggBackend`` (split prefill/decode pools). Convenience: pass
    ``engine_factory`` (+ ``n_replicas``) and the gateway builds the
    colocated backend itself.

    ``autoscale``: an :class:`~.autoscale.AutoscalePolicy` (or dict of
    its fields) — enables the scaling loop against this backend.
    """

    def __init__(self, engine_factory:
                 Optional[Callable[[], ServeEngine]] = None, *,
                 backend=None, n_replicas: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 autoscale=None, started: bool = True,
                 clock: Optional[Callable[[], float]] = None):
        if (backend is None) == (engine_factory is None):
            raise ValueError(
                "pass exactly one of engine_factory / backend")
        if backend is None:
            backend = ReplicaSet(
                engine_factory,
                n_replicas if n_replicas is not None else env_int(
                    "MXTPU_GATEWAY_REPLICAS", 1,
                    "Engine replicas the gateway starts by default "
                    "(scale_to / the autoscaler move it at runtime)."),
                started=started)
        self.backend = backend
        self.queue_max = (queue_max if queue_max is not None
                          else env_int(
                              "MXTPU_GATEWAY_QUEUE_MAX", 64,
                              "Gateway admission bound: requests "
                              "queued (not yet seated in a slot) "
                              "beyond this are refused with 429 + "
                              "Retry-After."))
        dflt = (default_deadline_s if default_deadline_s is not None
                else env_float(
                    "MXTPU_GATEWAY_DEADLINE_S", 0.0,
                    "Default per-request deadline (seconds) the "
                    "gateway applies when a request does not set one; "
                    "0 disables."))
        self.default_deadline_s = dflt if dflt and dflt > 0 else None
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._closed = False
        self._m_requests: Dict[str, Any] = {}
        self._m_depth = telemetry.gauge(
            "gateway_queue_depth",
            "Requests accepted by the gateway, not yet seated")
        self._m_ttft = telemetry.histogram(
            "gateway_ttft_ms",
            "Time to first token, submission to first on_token")
        self._http = None
        self._scaler = None
        self._scaler_stop: Optional[threading.Event] = None
        if autoscale is not None:
            from .autoscale import Autoscaler, AutoscalePolicy
            policy = (autoscale if isinstance(autoscale, AutoscalePolicy)
                      else AutoscalePolicy(**dict(autoscale)))
            self._scaler = Autoscaler(self.backend, policy,
                                      clock=self._clock)
            self._scaler_stop = threading.Event()
            threading.Thread(target=self._scaler.run_forever,
                             args=(self._scaler_stop,), daemon=True,
                             name="mxtpu-gw-autoscale").start()

    def _count(self, code: str) -> None:
        m = self._m_requests.get(code)
        if m is None:
            m = self._m_requests[code] = telemetry.counter(
                "gateway_requests_total",
                "Requests at the gateway front door, by outcome code",
                code=code)
        m.inc()

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0,
               deadline_s: Optional[float] = None) -> RequestHandle:
        """Admission-check + route; returns the streaming handle.
        Raises :class:`GatewayOverloaded` past the queue bound and
        ``ValueError`` on invalid parameters (the front door maps
        these to 429 / 400)."""
        handle = RequestHandle(self, time.perf_counter())
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p),
            seed=int(seed), on_token=handle._on_token,
            on_done=handle._on_done,
            deadline_s=(deadline_s if deadline_s is not None
                        else self.default_deadline_s))
        # ONE critical section from depth check to enqueue: every
        # front-door thread races submit under overload, and an
        # unsynchronized check-then-route would admit a whole
        # thundering herd past the bound before any of them enqueued
        with self._lock:
            load = self.backend.load_total()
            depth = load["queued"]
            self._m_depth.set(depth)
            if depth >= self.queue_max:
                # Retry-After ≈ one queue-drain: pending seats over
                # total slot throughput is unknowable without a
                # latency model, so use pending/slots "generations"
                retry = max(1, round(depth / max(1, load["slots"])))
                self._count("429")
                telemetry.flight().record("gateway", "shed",
                                          depth=depth,
                                          bound=self.queue_max)
                raise GatewayOverloaded(depth, self.queue_max, retry)
            try:
                handle.ticket = self.backend.route(req)
            except ValueError:
                self._count("400")
                raise
        self._count("accepted")
        return handle

    def submit_dict(self, body: Dict[str, Any]) -> RequestHandle:
        """The front door's JSON surface: validates types, forwards
        known fields."""
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        if "prompt" not in body:
            raise ValueError("missing 'prompt'")
        prompt = body["prompt"]
        if not isinstance(prompt, (list, tuple)) or not all(
                isinstance(t, int) for t in prompt):
            raise ValueError("'prompt' must be a list of ints")
        return self.submit(
            np.asarray(prompt, np.int32),
            int(body.get("max_new_tokens", 16)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=body.get("top_k"), top_p=body.get("top_p"),
            seed=int(body.get("seed", 0)),
            deadline_s=body.get("deadline_s"))

    # -- front door / lifecycle ---------------------------------------------
    def start_http(self, host: str = "127.0.0.1",
                   port: Optional[int] = None) -> int:
        """Bind + serve the HTTP front door on a daemon thread;
        returns the bound port (pass 0 for an ephemeral one)."""
        from .frontdoor import serve_http
        if port is None:
            port = env_int(
                "MXTPU_GATEWAY_PORT", 9300,
                "Default TCP port of the gateway HTTP front door.")
        self._http, bound = serve_http(self, host, port)
        return bound

    def refresh_gauges(self) -> None:
        """Point-in-time gauges are written on the submit path, which
        goes quiet exactly when a drained backlog should read 0 — the
        scrape endpoints re-read the source before exporting."""
        self._m_depth.set(self.backend.load_total()["queued"])

    def state(self) -> Dict[str, Any]:
        """Live topology snapshot (GET /state; tools/diagnose.py)."""
        load = self.backend.load_total()
        self._m_depth.set(load["queued"])
        return {"replicas": self.backend.state(),
                "n_replicas": self.backend.size,
                "queued": load["queued"], "active": load["active"],
                "slots": load["slots"], "queue_max": self.queue_max,
                "autoscaler": self._scaler.describe()
                if self._scaler else None}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._scaler_stop is not None:
            self._scaler_stop.set()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        self.backend.close()
